"""Single-path confinement rules — the six PR 3–9 AST guards, re-
expressed over the shared engine (tests/test_*.py used to carry one
hand-rolled ``ast.walk`` copy each; they now assert these rules).

Each rule pins an architectural chokepoint: ALL traffic of some kind
must flow through ONE module/class, because the chokepoint is where
the system's guarantees live (group commit, admission control, retry/
breaker policy, lease fencing, checksum verification, supervised
spawning, the metrics registry)."""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .engine import Finding, Project, rule

__all__ = ["RULES"]


def _class(module, name: str) -> Optional[ast.ClassDef]:
    for n in module.walk():
        if isinstance(n, ast.ClassDef) and n.name == name:
            return n
    return None


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


@rule("ingest-hot-path",
      "event-server write handlers must feed the ingest buffer — a "
      "direct per-event DAO insert bypasses group commit, drain and "
      "overload shedding")
def ingest_hot_path(project: Project) -> Iterable[Finding]:
    m = project.module("data/api/event_server.py")
    if m is None or m.tree is None:
        return
    disp = project.display_path(m)
    cls = _class(m, "EventServer")
    if cls is None:
        yield Finding("ingest-hot-path", disp, 1,
                      "class EventServer not found — the hot-path guard "
                      "has nothing to check (was it renamed?)")
        return
    hot = {"handle_create", "handle_batch", "handle_webhook"}
    seen = set()
    for fn in ast.walk(cls):
        if not isinstance(fn, ast.AsyncFunctionDef) or fn.name not in hot:
            continue
        seen.add(fn.name)
        uses_buffer = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr in ("insert", "insert_batch",
                                   "insert_canonical_lines"):
                    yield Finding(
                        "ingest-hot-path", disp, n.lineno,
                        f"{fn.name} calls the per-event DAO "
                        f"`.{n.func.attr}(` directly; route writes "
                        "through EventServer.ingest (the group-commit "
                        "buffer)")
            if isinstance(n, ast.Attribute) and n.attr == "ingest":
                uses_buffer = True
        if not uses_buffer:
            yield Finding("ingest-hot-path", disp, fn.lineno,
                          f"{fn.name} does not feed the ingest buffer")
    for missing in sorted(hot - seen):
        yield Finding("ingest-hot-path", disp, cls.lineno,
                      f"hot handler {missing} not found on EventServer — "
                      "renaming it silently drops the guard")


_BANNED_SUB = ("Popen", "run", "call", "check_call", "check_output")
_BANNED_OS = ("fork", "forkpty", "spawnv", "spawnve", "spawnl", "spawnlp",
              "spawnvp", "posix_spawn", "execv", "execve")
# the soak driver's whole job is launching the REAL topology (the
# supervised fronts it spawns are themselves the supervisors); it only
# ever builds argv for this repo's own console entry points
_SPAWN_ALLOWED = ("parallel/supervisor.py", "workflow/soak.py")


@rule("spawn-confinement",
      "parallel/ and workflow/ spawn processes only through "
      "parallel/supervisor.py (plus the soak scenario driver, whose "
      "test subject IS the spawned topology) — a side-channel launch "
      "escapes liveness monitoring, restart accounting and drain")
def spawn_confinement(project: Project) -> Iterable[Finding]:
    for sub in ("parallel/", "workflow/"):
        for m in project.modules(sub):
            if m.relpath in _SPAWN_ALLOWED or m.tree is None:
                continue
            disp = project.display_path(m)
            for node in m.walk():
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)):
                    continue
                if (f.value.id == "subprocess" and f.attr in _BANNED_SUB) \
                        or (f.value.id == "os" and f.attr in _BANNED_OS):
                    yield Finding(
                        "spawn-confinement", disp, node.lineno,
                        f"{f.value.id}.{f.attr}() outside "
                        "parallel/supervisor.py — route worker spawning "
                        "through the supervisor")


@rule("resilient-urlopen",
      "storage backends reach HTTP only through the resilience layer "
      "(retries, breakers, fault injection) — raw urlopen bypasses all "
      "three")
def resilient_urlopen(project: Project) -> Iterable[Finding]:
    def urlopen_lines(tree) -> list[int]:
        return [n.lineno for n in ast.walk(tree)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "urlopen"]

    for m in project.modules("data/storage/"):
        if m.tree is None:
            continue
        calls = urlopen_lines(m.tree)
        if not calls:
            continue
        allowed: set[int] = set()
        if m.relpath == "data/storage/http_backend.py":
            # urlopen is legal ONLY inside the resilient _Transport
            # (whose every path applies policy/breaker/faults)
            transport = _class(m, "_Transport")
            if transport is not None:
                allowed = set(urlopen_lines(transport))
        disp = project.display_path(m)
        for ln in calls:
            if ln not in allowed:
                yield Finding(
                    "resilient-urlopen", disp, ln,
                    "urlopen() outside the resilient transport — use "
                    "common.resilience.resilient_urlopen")


_WAL_SUFFIXES = (".wal", ".colseg", ".manifest")
_WAL_ALLOWED = ("data/api/event_log.py", "data/api/ingest_wal.py")
#: tiered-retention artifact names (the retired/ subdir and the cold
#: archive namespace) — exact string constants only, so prose in
#: docstrings never trips the rule; the tier lifecycle (retire sweep,
#: archive round-trip CRC, restore commit order) lives in event_log.py
_TIER_LITERALS = ("retired", "pio_eventlog_archive")


@rule("wal-suffix-confinement",
      "only event_log.py/ingest_wal.py may open .wal/.colseg/.manifest "
      "artifacts or the retired/archive tier paths — touching them "
      "elsewhere forks segment lifecycle (leases, quarantine, manifest "
      "commits, tier moves)")
def wal_suffix_confinement(project: Project) -> Iterable[Finding]:
    for sub in ("data/", "workflow/"):
        for m in project.modules(sub):
            if m.relpath in _WAL_ALLOWED or m.tree is None:
                continue
            disp = project.display_path(m)
            for node in m.walk():
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    continue
                if node.value.endswith(_WAL_SUFFIXES):
                    yield Finding(
                        "wal-suffix-confinement", disp, node.lineno,
                        f"segment/manifest suffix {node.value!r} "
                        "referenced outside event_log.py/ingest_wal.py")
                elif node.value in _TIER_LITERALS:
                    yield Finding(
                        "wal-suffix-confinement", disp, node.lineno,
                        f"retention-tier artifact name {node.value!r} "
                        "referenced outside event_log.py — retire/"
                        "archive/restore only through its tier API")


_COUNTERISH = ("count", "counter", "stat", "stats", "metric")
_BANNED_CTOR = ("Counter", "defaultdict", "dict", "OrderedDict")


@rule("no-adhoc-counters",
      "no module-level counter dicts under data/api/ and workflow/ — "
      "ad-hoc counting state belongs to the telemetry registry")
def no_adhoc_counters(project: Project) -> Iterable[Finding]:
    for sub in ("data/api/", "workflow/"):
        for m in project.modules(sub):
            if m.tree is None or "/" in m.relpath[len(sub):]:
                continue  # top level of each dir, like the legacy guard
            disp = project.display_path(m)
            for node in m.tree.body:
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                else:
                    continue
                value = node.value
                banned = isinstance(value, (ast.Dict, ast.Set)) or (
                    isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in _BANNED_CTOR)
                if not banned:
                    continue
                for t in targets:
                    if (isinstance(t, ast.Name) and any(
                            s in t.id.lower() for s in _COUNTERISH)):
                        yield Finding(
                            "no-adhoc-counters", disp, node.lineno,
                            f"module-level counter dict {t.id!r} — use a "
                            "common/telemetry.py registry family")


@rule("models-dao-confinement",
      "workflow/ reads model blobs only through model_artifact.py — any "
      "other Models-DAO touch bypasses checksum verification and reopens "
      "the corrupt-model-serves-production hole")
def models_dao_confinement(project: Project) -> Iterable[Finding]:
    for m in project.modules("workflow/"):
        if m.relpath == "workflow/model_artifact.py" or m.tree is None:
            continue
        disp = project.display_path(m)
        for node in m.walk():
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if name == "get_model_data_models":
                yield Finding(
                    "models-dao-confinement", disp, node.lineno,
                    "get_model_data_models outside model_artifact.py — "
                    "read models via model_artifact.read_model")


#: the resident-cache internals only workflow/multitenant.py may touch:
#: the LRU ordered dict and the eviction victim scan. Everything else
#: goes through TenantMux's public surface (admit/ensure_loaded/
#: release/...), because the public surface is where the isolation
#: guarantees live — refcounted eviction ("never drop a tenant
#: mid-query"), per-tenant pins, the admission budget.
_TENANT_INTERNALS = ("_resident_lru", "_evict_victim")


@rule("tenant-confinement",
      "only workflow/multitenant.py touches the multi-tenant "
      "resident-cache internals (_resident_lru / _evict_victim) — a "
      "side-channel cache touch skips the eviction refcount and the "
      "per-tenant pin/budget isolation")
def tenant_confinement(project: Project) -> Iterable[Finding]:
    chokepoint = project.module("workflow/multitenant.py")
    if chokepoint is None or chokepoint.tree is None:
        return  # scoped scan without the mux module
    if not any(
            isinstance(n, (ast.Attribute, ast.Name))
            and getattr(n, "attr", getattr(n, "id", None))
            == "_resident_lru" for n in chokepoint.walk()):
        yield Finding(
            "tenant-confinement", project.display_path(chokepoint), 1,
            "resident-cache chokepoint (_resident_lru in "
            "workflow/multitenant.py) not found — renamed? The "
            "confinement guard has nothing to protect")
        return
    for m in project.modules(""):
        if m.relpath == "workflow/multitenant.py" or m.tree is None:
            continue
        disp = project.display_path(m)
        for node in m.walk():
            name = None
            if isinstance(node, ast.Attribute):
                name = node.attr
            elif isinstance(node, ast.Name):
                name = node.id
            if name in _TENANT_INTERNALS:
                yield Finding(
                    "tenant-confinement", disp, node.lineno,
                    f"{name} outside workflow/multitenant.py — go "
                    "through TenantMux's public surface "
                    "(admit/ensure_loaded/release/snapshot)")


@rule("query-dispatch-gate",
      "engine-server handlers route query compute only through the "
      "admission gate (_dispatch_query) — direct executor dispatch "
      "bypasses the bounded executor, shedding and deadline budget")
def query_dispatch_gate(project: Project) -> Iterable[Finding]:
    m = project.module("workflow/create_server.py")
    if m is None or m.tree is None:
        return
    disp = project.display_path(m)
    cls = _class(m, "EngineServer")
    if cls is None:
        yield Finding("query-dispatch-gate", disp, 1,
                      "class EngineServer not found — the dispatch guard "
                      "has nothing to check (was it renamed?)")
        return

    def mentions_query_compute(node) -> bool:
        return any(isinstance(sub, ast.Attribute)
                   and sub.attr in ("query", "batch_query")
                   for sub in ast.walk(node))

    gated = False
    for fn in ast.walk(cls):
        if not isinstance(fn, ast.AsyncFunctionDef) \
                or not fn.name.startswith("handle_"):
            continue
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in ("to_thread", "run_in_executor", "submit") and \
                    any(mentions_query_compute(a) for a in n.args):
                yield Finding(
                    "query-dispatch-gate", disp, n.lineno,
                    f"{fn.name} ships query compute to {name}() directly; "
                    "route it through EngineServer._dispatch_query")
            if fn.name == "handle_query" and name == "_dispatch_query":
                gated = True
    if not gated:
        yield Finding("query-dispatch-gate", disp, cls.lineno,
                      "handle_query no longer routes through "
                      "_dispatch_query")


#: the one models/ module allowed to touch ops.sharded_topk internals
_SHARDED_TOPK_FACADE = "models/_sharded_serving.py"


@rule("sharded-topk-confinement",
      "template code under models/ touches ops.sharded_topk internals "
      "only through the models/_sharded_serving.py facade — the "
      "mesh/host/flat layout choice (and its bit-identity contract) "
      "lives in exactly one place")
def sharded_topk_confinement(project: Project) -> Iterable[Finding]:
    for m in project.modules("models/"):
        if m.relpath == _SHARDED_TOPK_FACADE or m.tree is None:
            continue
        disp = project.display_path(m)
        for node in m.walk():
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if (mod == "sharded_topk" or mod.endswith(".sharded_topk")
                        or any(a.name == "sharded_topk"
                               for a in node.names)):
                    yield Finding(
                        "sharded-topk-confinement", disp, node.lineno,
                        "import from ops.sharded_topk outside the "
                        "_sharded_serving facade — score through "
                        "ShardedCatalog/ShardedIndicators instead")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith("sharded_topk"):
                        yield Finding(
                            "sharded-topk-confinement", disp, node.lineno,
                            "import of ops.sharded_topk outside the "
                            "_sharded_serving facade — score through "
                            "ShardedCatalog/ShardedIndicators instead")
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "sharded_topk"):
                yield Finding(
                    "sharded-topk-confinement", disp, node.lineno,
                    f"sharded_topk.{node.attr} referenced outside the "
                    "_sharded_serving facade — score through "
                    "ShardedCatalog/ShardedIndicators instead")


#: merged-view scan entries + shard-file access primitives banned on
#: the training path (see train_feed_confinement)
_FEED_BANNED_REFS = ("_merged_scan", "shard_paths", "scan_log_file")
_FEED_BANNED_CALLS = ("find_batches",)


@rule("train-feed-confinement",
      "training-path modules under workflow/ and ops/ must not read "
      "events through the merged JSON view (_merged_scan / "
      "find_batches) or touch shard files directly (shard_paths / "
      "scan_log_file) — the partition-feed reader API "
      "(data/api/partition_feed.py) is the one sanctioned shard "
      "access, so gang training provably reads zero merged bytes")
def train_feed_confinement(project: Project) -> Iterable[Finding]:
    for sub in ("workflow/", "ops/"):
        for m in project.modules(sub):
            if m.tree is None:
                continue
            disp = project.display_path(m)
            for node in m.walk():
                name = None
                if isinstance(node, ast.Attribute):
                    name = node.attr
                elif isinstance(node, ast.Name):
                    name = node.id
                if name in _FEED_BANNED_REFS:
                    yield Finding(
                        "train-feed-confinement", disp, node.lineno,
                        f"{name} referenced on the training path — "
                        "read events via data/api/partition_feed.py "
                        "(or the row-level store APIs), never the "
                        "merged scan or raw shard files")
                if isinstance(node, ast.Call) \
                        and _call_name(node) in _FEED_BANNED_CALLS:
                    yield Finding(
                        "train-feed-confinement", disp, node.lineno,
                        f"{_call_name(node)}() on the training path — "
                        "the merged-view batch scan bypasses the "
                        "partition feed; use "
                        "data/api/partition_feed.py")


#: the elastic-topology scale entry points: supervisor dynamic
#: membership (add_worker/retire_worker) and the coordinator's fenced
#: scale-directive writes (apply_scale/set_replicas). Only the elastic
#: control loop (workflow/fleet.py hosts it; workflow/elastic.py is the
#: pure decision function), the event-tier rescaler (data/api/
#: event_log.py) and the supervisor itself may call them — a side-
#: channel scale call skips drain-before-SIGTERM ordering, the
#: epoch-fenced decision log, and readiness withdrawal.
_SCALE_ENTRY_POINTS = ("add_worker", "retire_worker",
                       "apply_scale", "set_replicas")
_SCALE_ALLOWED = ("workflow/elastic.py", "workflow/fleet.py",
                  "data/api/event_log.py", "parallel/supervisor.py")


@rule("scale-directive-confinement",
      "only the elastic control loop (workflow/elastic.py + the fleet "
      "coordinator in workflow/fleet.py), the event-tier rescaler and "
      "the supervisor may call scale entry points (add_worker/"
      "retire_worker) or write scale directive rows (apply_scale/"
      "set_replicas) — a side-channel scale call skips drain ordering, "
      "readiness withdrawal and the fenced decision log")
def scale_directive_confinement(project: Project) -> Iterable[Finding]:
    chokepoint = project.module("workflow/fleet.py")
    if chokepoint is None or chokepoint.tree is None:
        return  # scoped scan without the fleet module
    if not any(isinstance(n, ast.Call)
               and _call_name(n) == "apply_scale"
               for n in chokepoint.walk()):
        yield Finding(
            "scale-directive-confinement",
            project.display_path(chokepoint), 1,
            "scale chokepoint (apply_scale in workflow/fleet.py) not "
            "found — renamed? The confinement guard has nothing to "
            "protect")
        return
    for m in project.modules(""):
        if m.relpath in _SCALE_ALLOWED or m.tree is None:
            continue
        disp = project.display_path(m)
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in _SCALE_ENTRY_POINTS:
                yield Finding(
                    "scale-directive-confinement", disp, node.lineno,
                    f"{name}() outside the elastic control loop — "
                    "scale only via the autoscaler (workflow/"
                    "elastic.py decisions applied by workflow/"
                    "fleet.py) or `pio eventserver scale`")


RULES = [ingest_hot_path, spawn_confinement, resilient_urlopen,
         wal_suffix_confinement, no_adhoc_counters, models_dao_confinement,
         tenant_confinement, query_dispatch_gate,
         sharded_topk_confinement, train_feed_confinement,
         scale_directive_confinement]
