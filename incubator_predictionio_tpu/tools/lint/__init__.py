"""``pio lint`` — the repo's static-analysis pass.

See :mod:`.engine` for the model (one parse per module, declarative
rules, checked suppressions) and docs/operations.md "Static analysis"
for the operator surface. Rule catalog::

    from incubator_predictionio_tpu.tools.lint import ALL_RULES
"""

from __future__ import annotations

from .engine import (Finding, Module, Project, Rule, report_json, rule,
                     run_lint)
from . import (rules_concurrency, rules_confinement, rules_flow,
               rules_registry)

__all__ = ["ALL_RULES", "Finding", "Module", "Project", "Rule",
           "lint_repo", "report_json", "rule", "run_lint",
           "rule_names", "assert_rule_clean"]

ALL_RULES: list[Rule] = (rules_confinement.RULES
                         + rules_concurrency.RULES
                         + rules_registry.RULES
                         + rules_flow.RULES)


def rule_names() -> list[str]:
    return [r.name for r in ALL_RULES]


_project_cache: dict = {}
_full_result_cache: dict = {}


def lint_repo(repo_root=None, only=None) -> dict:
    """Run the full rule set (or ``only``) against this repo.

    The parsed Project is memoized per root: the tier-1 repo-clean test
    plus the seven migrated guard tests would otherwise each re-parse
    all ~116 modules — one parse pass total is the budget contract.
    FULL runs (``only=None``) memoize their whole result too: they are
    deterministic per process, and the repo-clean gate, the suppression
    inventory and the runtime-budget tests all want the same run — its
    ``timings`` carry the true cost (parse, call graph and tests/ scan
    are paid lazily inside the first rules that need them)."""
    project = _project_cache.get(repo_root)
    if project is None:
        project = _project_cache[repo_root] = Project.from_repo(repo_root)
    if only is None:
        result = _full_result_cache.get(repo_root)
        if result is None:
            result = _full_result_cache[repo_root] = run_lint(
                project, ALL_RULES)
        return result
    return run_lint(project, ALL_RULES, only=only)


def assert_rule_clean(*names: str) -> None:
    """Test helper: the repo must be clean under the named rule(s).

    The six legacy AST-guard tests route through this — same coverage,
    one engine, zero duplicated ast.walk code. Raises AssertionError
    listing every finding."""
    result = lint_repo(only=list(names))
    findings = result["findings"]
    assert not findings, (
        f"pio lint rule(s) {', '.join(names)} found "
        f"{len(findings)} violation(s):\n"
        + "\n".join(f.render() for f in findings))
