"""Registry-sync rules: knobs, fault points and metric names must
match convention AND the operator docs — both directions.

The operator surface (``PIO_*`` env knobs, ``faultinject`` point
names, ``pio_*`` telemetry families) is documented in ``docs/``; these
rules fail lint whenever code and docs drift, so "update the knob
table" stops being a review-time memory test. PAPER.md §0: upstream
PredictionIO leaned on Scala's compiler for this class of contract —
in untyped Python the lint pass is the compiler we get to keep."""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .engine import Finding, Module, Project, rule

__all__ = ["RULES"]

_PIO_KNOB = re.compile(r"^PIO_[A-Z0-9_]+$")
_DOC_KNOB_ROW = re.compile(r"^\|(?P<cell>[^|]*`PIO_[A-Z0-9_]+`[^|]*)\|")
_DOC_KNOB_NAME = re.compile(r"`(PIO_[A-Z0-9_]+)`")
_FAULT_POINT = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
_METRIC = re.compile(r"^pio_[a-z][a-z0-9_]*$")

_ENV_FNS = ("env_int", "env_float", "env_ms", "env_flag", "env_str")

# the linter's own sources mention the very patterns it hunts
_SELF = "tools/lint/"


def _skip(m: Module) -> bool:
    return m.tree is None or m.relpath.startswith(_SELF)


def _env_read(node: ast.AST) -> Optional[tuple[str, int]]:
    """(knob, line) when ``node`` reads a PIO_* env var directly:
    ``os.environ.get("PIO_X")``, ``os.getenv("PIO_X")`` or
    ``os.environ["PIO_X"]`` (load context). Dynamic names (f-strings,
    ``PIO_STORAGE_SOURCES_%s``-style config families, reads through a
    variable) are invisible to static analysis and out of scope."""
    if isinstance(node, ast.Call):
        f = node.func
        lit = (node.args[0].value
               if node.args and isinstance(node.args[0], ast.Constant)
               and isinstance(node.args[0].value, str) else None)
        if lit is None or not _PIO_KNOB.match(lit):
            return None
        if isinstance(f, ast.Attribute) and f.attr == "get" \
                and isinstance(f.value, ast.Attribute) \
                and f.value.attr == "environ" \
                and isinstance(f.value.value, ast.Name):
            return lit, node.lineno
        if isinstance(f, ast.Attribute) and f.attr == "getenv" \
                and isinstance(f.value, ast.Name):
            return lit, node.lineno
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and isinstance(node.value, ast.Attribute) \
            and node.value.attr == "environ" \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str) \
            and _PIO_KNOB.match(node.slice.value):
        return node.slice.value, node.lineno
    return None


def _envknobs_read(node: ast.AST) -> Optional[tuple[str, int]]:
    """(knob, line) when ``node`` parses a PIO_* knob via envknobs."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else ""
    if name not in _ENV_FNS or not node.args:
        return None
    a0 = node.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
            and _PIO_KNOB.match(a0.value):
        return a0.value, node.lineno
    return None


@rule("knob-envknobs",
      "every PIO_* env knob is parsed through common/envknobs.py — one "
      "tolerant parser, one malformed-value policy, instead of a fourth "
      "divergent copy of _env_int")
def knob_envknobs(project: Project) -> Iterable[Finding]:
    for m in project.modules():
        if _skip(m) or m.relpath == "common/envknobs.py":
            continue
        disp = project.display_path(m)
        for node in m.walk():
            hit = _env_read(node)
            if hit is not None:
                knob, line = hit
                yield Finding(
                    "knob-envknobs", disp, line,
                    f"{knob} read directly from os.environ — parse it "
                    "via common/envknobs.py (env_int/env_float/env_ms/"
                    "env_flag/env_str)")


def _code_knobs(project: Project) -> dict[str, tuple[str, int]]:
    """Every PIO_* knob the package READS (direct or envknobs), mapped
    to its first read site."""
    out: dict[str, tuple[str, int]] = {}
    for m in project.modules():
        if _skip(m):
            continue
        disp = project.display_path(m)
        for node in m.walk():
            hit = _env_read(node) or _envknobs_read(node)
            if hit is not None:
                out.setdefault(hit[0], (disp, hit[1]))
    return out


def _doc_knob_rows(project: Project) -> dict[str, tuple[str, int]]:
    """Knob-table rows across docs/*.md: {knob: (docs path, line)}.
    A name ending in ``_`` documents a prefix family (PIO_SSL_...)."""
    rows: dict[str, tuple[str, int]] = {}
    for fname, text in project.docs().items():
        for i, line in enumerate(text.splitlines(), 1):
            match = _DOC_KNOB_ROW.match(line.strip())
            if match:  # every knob named in the row's FIRST cell
                for name in _DOC_KNOB_NAME.findall(match.group("cell")):
                    rows.setdefault(name, (f"docs/{fname}", i))
    return rows


@rule("knob-docs-sync",
      "the PIO_* knob set and the docs knob tables agree: every knob "
      "the package reads has a table row, every table row names a knob "
      "that still exists in the repo")
def knob_docs_sync(project: Project) -> Iterable[Finding]:
    code = _code_knobs(project)
    rows = _doc_knob_rows(project)
    prefixes = tuple(k for k in rows if k.endswith("_"))
    for knob, (disp, line) in sorted(code.items()):
        if knob in rows or any(knob.startswith(p) for p in prefixes):
            continue
        yield Finding(
            "knob-docs-sync", disp, line,
            f"{knob} is read here but has no row in any docs knob "
            "table — document it (docs/operations.md)")
    if not rows and code:
        # docs missing entirely (seeded test trees get this instead of
        # a silent pass)
        return
    repo_text = project.repo_python_text()
    for knob, (docpath, line) in sorted(rows.items()):
        # prefix-family rows (PIO_SSL_...) probe as plain substrings too
        if knob not in repo_text:
            yield Finding(
                "knob-docs-sync", docpath, line,
                f"documented knob {knob} no longer appears anywhere in "
                "the repo's Python — delete the dead row")


@rule("fault-point-registry",
      "faultinject point names follow the dotted lowercase convention "
      "and are documented in docs/operations.md — an undocumented point "
      "is chaos tooling nobody can aim")
def fault_point_registry(project: Project) -> Iterable[Finding]:
    ops = project.docs().get("operations.md", "")
    for m in project.modules():
        if _skip(m):
            continue
        disp = project.display_path(m)
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if name not in ("fault_point", "stream_fault") or not node.args:
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                continue  # variable point names (resilience endpoints)
            point = a0.value
            if not _FAULT_POINT.match(point):
                yield Finding(
                    "fault-point-registry", disp, node.lineno,
                    f"fault point {point!r} breaks the "
                    "subsystem.operation naming convention")
            elif f"`{point}`" not in ops:
                yield Finding(
                    "fault-point-registry", disp, node.lineno,
                    f"fault point {point!r} is not documented in "
                    "docs/operations.md (fault-injection section)")


# C-ABI symbol names (pio_col_*, pio_pdd_*) and the upstream
# PredictionIO storage repository names (pio_metadata/eventdata/
# modeldata) are fixed wire/DB contracts, not telemetry families.
_METRIC_SKIP_DIRS = ("native/", "data/storage/")
_METRIC_ALLOW = frozenset({
    "pio_pr",  # server-generated entity_type prefix (wire protocol)
})


@rule("metric-name-registry",
      "telemetry family names follow the pio_* convention (counters end "
      "_total) and every family is documented — an undocumented metric "
      "is a dashboard nobody will build")
def metric_name_registry(project: Project) -> Iterable[Finding]:
    docs = project.docs()

    def documented(name: str) -> bool:
        # accept `name` and the labelled form `name{label,...}`
        probe = re.compile(rf"`{re.escape(name)}(?![a-z0-9_])")
        return any(probe.search(text) for text in docs.values())

    for m in project.modules():
        if _skip(m) or m.relpath.startswith(_METRIC_SKIP_DIRS):
            continue
        # family names reach the registry in too many shapes for call-
        # site anchoring alone (collector loops build GaugeFamily from
        # name tuples), so the scan covers every pio_* snake literal —
        # but only in modules that actually touch telemetry, so a
        # pio_*-shaped wire constant elsewhere isn't misread as an
        # undocumented family
        if "telemetry" not in m.source:
            continue
        disp = project.display_path(m)
        # ContextVar debug names are runtime identifiers, not families —
        # exempt their first args so they never force a rename
        ctxvar_names = {
            n.args[0].value for n in m.walk()
            if isinstance(n, ast.Call) and n.args
            and isinstance(n.args[0], ast.Constant)
            and isinstance(n.args[0].value, str)
            and (getattr(n.func, "attr", "") == "ContextVar"
                 or getattr(n.func, "id", "") == "ContextVar")}
        seen: set[str] = set()
        for node in m.walk():
            # counters must end _total (Prometheus convention)
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "counter" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    cname = node.args[0].value
                    if _METRIC.match(cname) \
                            and not cname.endswith("_total"):
                        yield Finding(
                            "metric-name-registry", disp, node.lineno,
                            f"counter family {cname!r} must end in "
                            "_total")
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            name = node.value
            if not _METRIC.match(name) or name in _METRIC_ALLOW \
                    or name in ctxvar_names or name in seen:
                continue
            seen.add(name)
            if not documented(name):
                yield Finding(
                    "metric-name-registry", disp, node.lineno,
                    f"telemetry family {name!r} is not documented in "
                    "docs/ (operations.md metrics table)")


# ---------------------------------------------------------------------------
# soak registries: the scenario driver's SLO/fault contracts stay live
# ---------------------------------------------------------------------------

_SOAK_MODULE = "workflow/soak.py"


def _module_const_strings(m: Module, name: str):
    """String literals of a module-level ``NAME = (...)`` tuple/list
    assignment: [(value, lineno)], or None when no such literal
    assignment exists."""
    for node in m.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            return [(e.value, e.lineno) for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return None


def _module_const_dict(m: Module, name: str):
    """{key: (value, lineno)} of a module-level ``NAME = {...}`` dict
    literal with string keys/values, or None when absent."""
    for node in m.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str) \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    out[k.value] = (v.value, v.lineno)
            return out
    return None


@rule("soak-slo-registry",
      "every telemetry family the soak driver asserts SLOs/evidence "
      "from (workflow/soak.py SLO_METRICS) is a documented metric "
      "family — a renamed family must not silently blind the scorecard")
def soak_slo_registry(project: Project) -> Iterable[Finding]:
    m = project.module(_SOAK_MODULE)
    if m is None or m.tree is None:
        return
    disp = project.display_path(m)
    entries = _module_const_strings(m, "SLO_METRICS")
    if entries is None:
        yield Finding(
            "soak-slo-registry", disp, 1,
            "SLO_METRICS tuple literal not found in workflow/soak.py — "
            "the soak SLO registry contract moved (rename breaks the "
            "lint coverage, restore the literal)")
        return
    docs = project.docs()

    def documented(name: str) -> bool:
        probe = re.compile(rf"`{re.escape(name)}(?![a-z0-9_])")
        return any(probe.search(text) for text in docs.values())

    for name, line in entries:
        if not _METRIC.match(name):
            yield Finding(
                "soak-slo-registry", disp, line,
                f"soak SLO metric {name!r} breaks the pio_* family "
                "naming convention")
        elif not documented(name):
            yield Finding(
                "soak-slo-registry", disp, line,
                f"soak SLO metric {name!r} is not a documented metric "
                "family (docs/operations.md metrics tables) — the "
                "scorecard would assert evidence from a family nobody "
                "exports")


def _armed_points(project: Project) -> set:
    """Every fault-point literal named in a fault_point()/stream_fault()
    call anywhere in the package (the armed set)."""
    out: set = set()
    for m in project.modules():
        if m.tree is None:
            continue
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if name in ("fault_point", "stream_fault") and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
    return out


@rule("soak-fault-registry",
      "every spec fault the soak scheduler can inject "
      "(workflow/soak.py FAULT_POINTS) names a fault point that is "
      "actually armed by a fault_point() call in the repo — a timeline "
      "aimed at a removed point would silently inject nothing")
def soak_fault_registry(project: Project) -> Iterable[Finding]:
    m = project.module(_SOAK_MODULE)
    if m is None or m.tree is None:
        return
    disp = project.display_path(m)
    mapping = _module_const_dict(m, "FAULT_POINTS")
    if mapping is None:
        yield Finding(
            "soak-fault-registry", disp, 1,
            "FAULT_POINTS dict literal not found in workflow/soak.py — "
            "the soak fault registry contract moved (rename breaks the "
            "lint coverage, restore the literal)")
        return
    armed = _armed_points(project)
    for fault, (point, line) in sorted(mapping.items()):
        if point not in armed:
            yield Finding(
                "soak-fault-registry", disp, line,
                f"soak fault {fault!r} schedules fault point {point!r}, "
                "which no fault_point()/stream_fault() call arms "
                "anywhere — the scheduled rule would never fire")


RULES = [knob_envknobs, knob_docs_sync, fault_point_registry,
         metric_name_registry, soak_slo_registry, soak_fault_registry]
