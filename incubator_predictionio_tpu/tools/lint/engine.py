"""`pio lint` rule engine: one AST parse per module, declarative rules.

PRs 3–9 each shipped a bespoke AST-guard test (single-dispatch-path,
single-spawn-path, no-raw-urlopen, WAL-suffix confinement, no-ad-hoc
counters, Models-DAO confinement — six hand-rolled ``ast.walk`` copies
across ``tests/``), while review passes kept hand-catching the same
defect classes: unguarded lock-shared state, blocking calls on the
event loop, and knobs/fault-points/metric names drifting from
``docs/operations.md``. This package turns those conventions into an
enforced checker: every module is parsed ONCE into a :class:`Project`,
rules are small functions over the parsed forest, findings carry
file:line anchors, and per-line suppressions are themselves checked
(an unused suppression is a finding — dead exemptions can't
accumulate).

Deliberately jax-free and import-light: the engine reads SOURCE, it
never imports the modules it checks, so ``pio lint`` stays fast enough
to run as a tier-1 test (docs/operations.md "Static analysis").

Suppression syntax (per physical line, reason recommended)::

    something_exempt()  # pio-lint: disable=rule-name -- why it is safe
    other()             # pio-lint: disable=rule-a,rule-b -- shared reason
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
import time
from typing import Callable, Iterable, Optional

__all__ = [
    "Finding", "Module", "Project", "Rule", "rule", "run_lint",
    "PACKAGE_NAME",
]

PACKAGE_NAME = "incubator_predictionio_tpu"

# rule names reserved by the engine itself (not declarative rules)
PARSE_ERROR = "parse-error"
UNUSED_SUPPRESSION = "unused-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*pio-lint:\s*disable=([A-Za-z0-9_,-]+)(?:\s+--\s*(.*\S))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a repo-relative file:line."""

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    """A ``# pio-lint: disable=`` comment found in a module."""

    path: str               # repo-relative
    line: int
    rules: tuple[str, ...]
    reason: str
    used: set = dataclasses.field(default_factory=set)  # rule names hit


class Module:
    """One parsed source file. ``tree`` is None when parsing failed
    (the engine reports that as a ``parse-error`` finding — a module
    the compiler can't read is a module no rule can vouch for)."""

    def __init__(self, path: pathlib.Path, relpath: str):
        self.path = path
        self.relpath = relpath          # relative to the PACKAGE root
        self.source = path.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=str(path))
        except SyntaxError as e:  # pragma: no cover — repo always parses
            self.parse_error = f"{e.msg} (line {e.lineno})"

    def walk(self) -> Iterable[ast.AST]:
        return ast.walk(self.tree) if self.tree is not None else ()


class Project:
    """The parsed package + the docs it must stay in sync with.

    Parsing is done lazily and exactly once per file; rules receive the
    same Project instance, so a full ``pio lint`` run is a single parse
    pass over the package (the tier-1 budget constraint)."""

    def __init__(self, repo_root: pathlib.Path,
                 pkg_root: Optional[pathlib.Path] = None,
                 docs_dir: Optional[pathlib.Path] = None):
        self.repo_root = pathlib.Path(repo_root)
        self.pkg_root = pathlib.Path(
            pkg_root if pkg_root is not None
            else self.repo_root / PACKAGE_NAME)
        self.docs_dir = pathlib.Path(
            docs_dir if docs_dir is not None else self.repo_root / "docs")
        self._modules: Optional[dict[str, Module]] = None
        self._docs: Optional[dict[str, str]] = None
        self._repo_py_text: Optional[str] = None

    @classmethod
    def from_repo(cls, repo_root=None) -> "Project":
        if repo_root is None:
            # tools/lint/engine.py → package root is three parents up
            pkg = pathlib.Path(__file__).resolve().parent.parent.parent
            repo_root = pkg.parent
        return cls(pathlib.Path(repo_root))

    # -- package sources ---------------------------------------------------
    def modules(self, under: str = "") -> list[Module]:
        """All package modules, or those whose relpath starts with
        ``under`` (posix prefix like ``"data/api/"``)."""
        if self._modules is None:
            mods = {}
            for path in sorted(self.pkg_root.rglob("*.py")):
                if "__pycache__" in path.parts:
                    continue
                rel = path.relative_to(self.pkg_root).as_posix()
                mods[rel] = Module(path, rel)
            self._modules = mods
        if not under:
            return list(self._modules.values())
        return [m for r, m in self._modules.items() if r.startswith(under)]

    def module(self, relpath: str) -> Optional[Module]:
        self.modules()
        assert self._modules is not None
        return self._modules.get(relpath)

    def display_path(self, module: Module) -> str:
        """Repo-relative path for findings (clickable in terminals)."""
        try:
            return module.path.relative_to(self.repo_root).as_posix()
        except ValueError:  # pkg outside repo root (seeded test trees)
            return f"{PACKAGE_NAME}/{module.relpath}"

    # -- docs --------------------------------------------------------------
    def docs(self) -> dict[str, str]:
        """{filename: text} for every markdown file under docs/."""
        if self._docs is None:
            self._docs = {}
            if self.docs_dir.is_dir():
                for p in sorted(self.docs_dir.glob("*.md")):
                    self._docs[p.name] = p.read_text(encoding="utf-8")
        return self._docs

    def docs_line(self, filename: str, needle: str) -> int:
        """1-based line of the first occurrence of ``needle`` in a docs
        file (0 when absent) — used to anchor docs-side findings."""
        text = self.docs().get(filename, "")
        for i, line in enumerate(text.splitlines(), 1):
            if needle in line:
                return i
        return 0

    # -- repo-wide literal search (docs dead-row check) --------------------
    def repo_python_text(self) -> str:
        """Concatenated text of every tracked .py file in the repo
        (package + tools + bench + tests): the existence oracle for
        documented knobs that live outside the package."""
        if self._repo_py_text is None:
            chunks = []
            for pattern in ("*.py", "tools/*.py", "tests/*.py",
                            "templates/**/*.py"):
                for p in sorted(self.repo_root.glob(pattern)):
                    if "__pycache__" in p.parts:
                        continue
                    try:
                        chunks.append(p.read_text(encoding="utf-8"))
                    except OSError:  # pragma: no cover
                        pass
            for m in self.modules():
                chunks.append(m.source)
            self._repo_py_text = "\n".join(chunks)
        return self._repo_py_text

    # -- suppressions ------------------------------------------------------
    def suppressions(self) -> dict[tuple[str, int], Suppression]:
        out = {}
        for m in self.modules():
            if m.relpath.startswith("tools/lint/"):
                continue  # the linter's own docs show the syntax
            disp = self.display_path(m)
            for i, line in enumerate(m.lines, 1):
                match = _SUPPRESS_RE.search(line)
                if match is None:
                    continue
                rules = tuple(
                    r.strip() for r in match.group(1).split(",") if r.strip())
                out[(disp, i)] = Suppression(
                    disp, i, rules, (match.group(2) or "").strip())
        return out


class Rule:
    """A named check over a :class:`Project`. ``fn(project)`` yields
    :class:`Finding`s; ``rationale`` is the one-line catalog entry."""

    def __init__(self, name: str, rationale: str,
                 fn: Callable[[Project], Iterable[Finding]]):
        self.name = name
        self.rationale = rationale
        self._fn = fn

    def check(self, project: Project) -> list[Finding]:
        return list(self._fn(project))


def rule(name: str, rationale: str):
    """Decorator: register a generator function as a Rule."""
    def deco(fn):
        return Rule(name, rationale, fn)
    return deco


def run_lint(project: Project, rules: list[Rule],
             only: Optional[Iterable[str]] = None) -> dict:
    """Run ``rules`` (optionally restricted to the ``only`` names) over
    ``project``. Returns::

        {"findings": [Finding...],       # post-suppression, sorted
         "suppressed": int,
         "suppressions": [Suppression...],
         "rules": [names run],
         "timings": [(rule name, seconds)],   # per-rule wall time
         "modules": int}

    Per-line ``# pio-lint: disable=<rule>`` comments swallow findings
    of that rule on that physical line. On a FULL run (``only`` is
    None) every suppression must have earned its keep: a disable
    comment whose rule produced no finding on that line — or that
    names an unknown rule — becomes an ``unused-suppression`` finding,
    so stale exemptions surface instead of silently rotting. Restricted
    runs skip that check (a single rule can't know what the others
    would have hit).
    """
    known = {r.name for r in rules}
    if only is not None:
        wanted = set(only)
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule(s): {', '.join(sorted(unknown))}; "
                f"known: {', '.join(sorted(known))}")
        selected = [r for r in rules if r.name in wanted]
    else:
        selected = list(rules)

    raw: list[Finding] = []
    timings: list[tuple[str, float]] = []
    for r in selected:
        t0 = time.perf_counter()
        raw.extend(r.check(project))
        timings.append((r.name, time.perf_counter() - t0))
    # modules the compiler can't parse are findings, not crashes
    for m in project.modules():
        if m.parse_error is not None:
            raw.append(Finding(PARSE_ERROR, project.display_path(m),
                               1, f"syntax error: {m.parse_error}"))

    sups = project.suppressions()
    kept: list[Finding] = []
    suppressed = 0
    for f in raw:
        s = sups.get((f.path, f.line))
        if s is not None and f.rule in s.rules:
            s.used.add(f.rule)
            suppressed += 1
        else:
            kept.append(f)

    if only is None:
        for s in sups.values():
            for rname in s.rules:
                if rname in s.used:
                    continue
                why = ("unknown rule" if rname not in known
                       else "nothing to suppress here")
                kept.append(Finding(
                    UNUSED_SUPPRESSION, s.path, s.line,
                    f"suppression of {rname!r} is unused ({why}) — "
                    "delete it or fix the rule name"))

    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return {
        "findings": kept,
        "suppressed": suppressed,
        "suppressions": sorted(sups.values(), key=lambda s: (s.path, s.line)),
        "rules": [r.name for r in selected],
        "timings": timings,
        "modules": len(project.modules()),
    }


def report_json(result: dict) -> str:
    """Stable machine-readable form for ``pio lint --json``."""
    return json.dumps({
        "clean": not result["findings"],
        "findings": [f.to_json() for f in result["findings"]],
        "suppressed": result["suppressed"],
        "suppressions": [
            {"path": s.path, "line": s.line, "rules": list(s.rules),
             "reason": s.reason}
            for s in result["suppressions"]],
        "rules": result["rules"],
        "modules": result["modules"],
    }, indent=2, sort_keys=True)
