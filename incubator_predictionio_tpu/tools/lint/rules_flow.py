"""Whole-program flow rules over the :mod:`.callgraph`.

The PR 10 rules are lexical — one function at a time.  These four see
the whole program, which is where the defect classes that actually
bit PRs 6–10 live: a blocking call reached *through a helper* on the
event loop, nested lock orders that invert only across functions, a
threading lock held across an ``await``, and chaos fault points no
test ever arms.

- **transitive-blocking-on-loop** — async defs of the serving modules
  must not REACH a known-blocking stdlib call through any uncut sync
  call chain.  Chains of length 1 (blocking directly in the async body)
  stay with the lexical ``no-blocking-on-loop`` rule; this one owns
  everything deeper.  Cut edges (``to_thread`` / ``run_in_executor`` /
  ``submit`` / ``Thread(target=)``) terminate the walk — that IS the
  fix the finding asks for.
- **lock-order** — the global acquisition-order graph (lexically
  nested ``with`` spans + call chains made while holding a lock) must
  be acyclic; a cycle is a potential deadlock that strikes only under
  the exact interleaving production traffic eventually supplies.  The
  same machinery flags re-acquiring a non-reentrant ``threading.Lock``
  already held on the call stack — not "potential": that one is a
  guaranteed self-deadlock.
- **lock-held-across-await** — a ``threading`` lock held across an
  ``await`` parks the LOOP on lock contention: every connection on the
  server stalls until the lock holder resumes.  (``asyncio.Lock`` +
  ``async with`` is the loop-native tool; or release before awaiting.)
- **fault-point-coverage** — every registered fault point must be
  armed by at least one test (a ``PIO_FAULT_SPEC`` /
  ``PIO_EVENT_WORKER_FAULT_SPEC`` literal under ``tests/``), closing
  the registry triangle: ``fault-point-registry`` syncs code ↔ docs,
  this syncs code ↔ tests.  An unarmed fault point is chaos tooling
  that silently stopped proving anything.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable

from .callgraph import graph_for
from .engine import Finding, Project, rule
from .rules_concurrency import _LOOP_SCOPES

__all__ = ["RULES"]


def _loop_modules(project: Project):
    mods = []
    for scope in _LOOP_SCOPES:
        if scope.endswith(".py"):
            m = project.module(scope)
            if m is not None:
                mods.append(m)
        else:
            mods.extend(project.modules(scope))
    return mods


def _disp(project: Project, relpath: str) -> str:
    m = project.module(relpath)
    return project.display_path(m) if m is not None else relpath


def _chain_render(graph, chain: tuple) -> str:
    parts = []
    for k in chain:
        fn = graph.node(k)
        parts.append(fn.qualname if fn is not None else k)
    return " → ".join(parts)


# Injected latency (faultinject's sleep) is EXEMPT by design: a
# latency fault must simulate the instrumented call being slow *at the
# call site*, including on-loop sites — that stall is the experiment,
# not a defect, and specs are only ever armed by the chaos harness.
_BLOCKING_EXEMPT = ("common/faultinject.py",)


@rule("transitive-blocking-on-loop",
      "async handlers of the serving modules must not REACH a blocking "
      "stdlib call through any sync call chain still on the event loop "
      "— a helper that blocks freezes every connection exactly like an "
      "inline call; to_thread/run_in_executor/Thread cut the walk")
def transitive_blocking_on_loop(project: Project) -> Iterable[Finding]:
    graph = graph_for(project)
    loop_rels = {m.relpath for m in _loop_modules(project)}
    # site -> (entry chain, n_entries) — one finding per blocking site,
    # however many handlers reach it (suppressions stay per-line)
    sites: dict = {}
    for fn in graph.functions.values():
        if not fn.is_async or fn.relpath not in loop_rels:
            continue
        for site, chain in graph.reachable_blocking(fn.key).items():
            if len(chain) < 2:
                continue    # direct hit: the lexical rule owns it
            if site[0].startswith(_BLOCKING_EXEMPT):
                continue    # injected latency: the fault IS the point
            if site in sites:
                sites[site] = (sites[site][0], sites[site][1] + 1)
            else:
                sites[site] = (chain, 1)
    for (rel, lineno, label), (chain, n) in sorted(sites.items()):
        extra = f" (+{n - 1} more async entry point(s))" if n > 1 else ""
        yield Finding(
            "transitive-blocking-on-loop", _disp(project, rel), lineno,
            f"blocking call {label}() runs on the event loop via "
            f"{_chain_render(graph, chain)}{extra} — ship it off-loop "
            "(asyncio.to_thread / run_in_executor) or cut the chain")


def _scc(nodes: set, edges: dict) -> list:
    """Tarjan strongly-connected components over the lock digraph.
    ``edges``: {(a, b): sites}.  Returns components as sorted tuples,
    only those with ≥ 2 nodes (self-loops are handled separately —
    reentrant locks make A→A legal)."""
    succ: dict = {}
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
    index: dict = {}
    low: dict = {}
    stack: list = []
    on_stack: set = set()
    out: list = []
    counter = [0]

    def strong(v):
        # iterative Tarjan: the lock graph is tiny, but recursion
        # limits are not a failure mode a linter may have
        work = [(v, iter(sorted(succ.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(tuple(sorted(comp)))

    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


@rule("lock-order",
      "the global lock acquisition-order graph (nested `with` spans + "
      "cross-function chains) must be acyclic, and a non-reentrant "
      "threading.Lock must never be re-acquired while held — cycles "
      "deadlock under the right interleaving, re-acquisition always")
def lock_order(project: Project) -> Iterable[Finding]:
    graph = graph_for(project)
    edges = graph.lock_order_edges()
    nodes = {a for a, _ in edges} | {b for _, b in edges}
    for comp in _scc(nodes, edges):
        witness = []
        anchor = None
        for (a, b), sites in sorted(edges.items()):
            if a in comp and b in comp and a != b:
                fnkey, lineno = sites[0]
                fn = graph.node(fnkey)
                if anchor is None:
                    anchor = (fn, fnkey, lineno)
                witness.append(
                    f"{graph.locks[a].render()} → "
                    f"{graph.locks[b].render()} in "
                    f"{fn.qualname if fn else fnkey}:{lineno}")
        if anchor is None:
            continue
        fn, fnkey, lineno = anchor
        rel = fn.relpath if fn is not None else fnkey.split("::")[0]
        yield Finding(
            "lock-order", _disp(project, rel), lineno,
            "inconsistent lock acquisition order — potential deadlock: "
            + "; ".join(witness)
            + " — pick ONE global order and stick to it")
    for lk, fnkey, lineno in sorted(graph.self_reacquires()):
        fn = graph.node(fnkey)
        rel = fn.relpath if fn is not None else fnkey.split("::")[0]
        yield Finding(
            "lock-order", _disp(project, rel), lineno,
            f"non-reentrant lock {graph.locks[lk].render()} is "
            f"re-acquired through a call made while already holding it "
            f"(in {fn.qualname if fn else fnkey}) — guaranteed "
            "self-deadlock; release first or use an RLock deliberately")


@rule("lock-held-across-await",
      "a threading lock held across an `await` stalls the WHOLE event "
      "loop whenever another thread holds the lock — release before "
      "awaiting, or use asyncio.Lock for loop-side exclusion")
def lock_held_across_await(project: Project) -> Iterable[Finding]:
    graph = graph_for(project)
    for fn in sorted(graph.functions.values(), key=lambda f: f.key):
        for lk, lineno in fn.across_await:
            info = graph.locks.get(lk)
            if info is None or info.kind not in ("thread", "rthread"):
                continue
            yield Finding(
                "lock-held-across-await", _disp(project, fn.relpath),
                lineno,
                f"threading lock {info.render()} is held across an "
                f"await in {fn.qualname} — under contention this parks "
                "the event loop itself; release before awaiting or use "
                "asyncio.Lock")


_FAULT_SPEC_ENVS = ("PIO_FAULT_SPEC", "PIO_EVENT_WORKER_FAULT_SPEC")


def _armed_literals(project: Project) -> frozenset:
    """Every string literal in a ``tests/**/*.py`` module that mentions
    a fault-spec env knob.  Memoized per Project (same contract as the
    parsed module forest)."""
    cached = getattr(project, "_fault_armed_literals", None)
    if cached is not None:
        return cached
    literals: set = set()
    tests_dir = pathlib.Path(project.repo_root) / "tests"
    if tests_dir.is_dir():
        for p in sorted(tests_dir.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            try:
                text = p.read_text(encoding="utf-8")
            except OSError:  # pragma: no cover
                continue
            if not any(env in text for env in _FAULT_SPEC_ENVS):
                continue
            try:
                tree = ast.parse(text)
            except SyntaxError:  # pragma: no cover — tier-1 parses
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    literals.add(node.value)
    project._fault_armed_literals = frozenset(literals)
    return project._fault_armed_literals


@rule("fault-point-coverage",
      "every registered fault point is armed by at least one test "
      "(PIO_FAULT_SPEC / PIO_EVENT_WORKER_FAULT_SPEC literal under "
      "tests/) — an unarmed point is chaos tooling that proves nothing")
def fault_point_coverage(project: Project) -> Iterable[Finding]:
    armed = _armed_literals(project)

    def is_armed(point: str) -> bool:
        return any(point in lit for lit in armed)

    seen: set = set()
    for m in project.modules():
        if m.tree is None or m.relpath.startswith("tools/lint/"):
            continue
        disp = project.display_path(m)
        for node in m.walk():
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if name not in ("fault_point", "stream_fault") or not node.args:
                continue
            a0 = node.args[0]
            if not (isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)):
                continue    # variable point names: out of static reach
            point = a0.value
            if point in seen:
                continue
            seen.add(point)
            if not is_armed(point):
                yield Finding(
                    "fault-point-coverage", disp, node.lineno,
                    f"fault point {point!r} is never armed by any test "
                    "— no PIO_FAULT_SPEC/PIO_EVENT_WORKER_FAULT_SPEC "
                    "literal under tests/ mentions it; add a chaos test "
                    "or delete the point")


RULES = [transitive_blocking_on_loop, lock_order, lock_held_across_await,
         fault_point_coverage]
