"""Concurrency rules: lock-guarded state and event-loop hygiene.

These mechanize the two defect classes review passes kept hand-
catching (PR 9's ``_pinned`` races, PR 5's on-loop WAL fsync freeze):

- **lock-discipline** — attributes REGISTERED as lock-guarded may only
  be read/mutated inside a lexical ``with <lock>:`` block. The
  registry (:data:`LOCK_GUARDED`) is the contract: adding an attribute
  there makes every unguarded access a finding, so the next
  "harmless" counter bump from a worker thread fails lint instead of
  losing increments in production.
- **no-blocking-on-loop** — known-blocking stdlib calls inside
  ``async def`` bodies of the serving modules. One blocked event loop
  freezes EVERY connection on that server, which is how the PR 5
  on-loop group-fsync froze the whole event server.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .engine import Finding, Project, rule

# _LOOP_SCOPES and the _BLOCKING_* tables are shared with the
# whole-program rules_flow family: ONE definition of "serving module"
# and "known-blocking call" or the lexical and flow rules drift apart.
__all__ = ["RULES", "LOCK_GUARDED", "_LOOP_SCOPES",
           "_BLOCKING_QUALIFIED", "_BLOCKING_BARE"]

# -- lock-discipline registry ----------------------------------------------
# module relpath -> list of (class name or None for module scope,
#                            lock attribute, guarded attributes)
#
# Seeded from the modules whose shared state crosses threads today:
# the engine server's lifecycle/admission counters are read by the
# telemetry collector from WHATEVER thread renders /metrics while the
# loop and reload worker threads mutate them; the ingest buffer's shed
# map is touched by loop-side admission and to_thread commit workers;
# the event-log lease fd is verified by commit threads while shutdown
# releases it; the supervisor heartbeat throttle is called from any
# worker thread. Construction-time writes (``__init__`` / ``_init_*``)
# are exempt — no second thread exists yet.
LOCK_GUARDED: dict[str, list[tuple[Optional[str], str, frozenset]]] = {
    "workflow/create_server.py": [
        ("EngineServer", "_lock", frozenset({
            "_pinned", "_pins_provisional", "_previous", "_rollbacks",
            "_swap_count", "_validate_failures", "_refresh_swaps"})),
        ("EngineServer", "_adm_lock", frozenset({
            "_adm_pending", "_adm_peak", "_shed_count", "_deadline_count",
            "_orphaned", "_draining", "_drain_stragglers"})),
    ],
    "data/api/ingest_buffer.py": [
        ("IngestBuffer", "_shed_lock", frozenset({"_shed"})),
    ],
    "data/api/event_log.py": [
        ("Lease", "_fd_lock", frozenset({"_fd"})),
    ],
    "parallel/supervisor.py": [
        (None, "_hb_lock", frozenset({"_hb_last", "_hb_interval"})),
    ],
}


def _with_locks(node: ast.With, classscope: bool) -> set[str]:
    """Lock names a ``with`` statement acquires: ``self.<name>`` in
    class scope, bare ``<name>`` at module scope."""
    out = set()
    for item in node.items:
        ce = item.context_expr
        if classscope and isinstance(ce, ast.Attribute) \
                and isinstance(ce.value, ast.Name) and ce.value.id == "self":
            out.add(ce.attr)
        elif not classscope and isinstance(ce, ast.Name):
            out.add(ce.id)
    return out


@rule("lock-discipline",
      "attributes registered as lock-guarded (LOCK_GUARDED) may only be "
      "touched inside a `with <lock>:` block — unguarded cross-thread "
      "access loses updates exactly like the PR 9 _pinned races")
def lock_discipline(project: Project) -> Iterable[Finding]:
    for relpath, entries in LOCK_GUARDED.items():
        m = project.module(relpath)
        if m is None or m.tree is None:
            continue
        disp = project.display_path(m)
        for classname, lock, attrs in entries:
            if classname is not None:
                scope = next(
                    (n for n in m.walk() if isinstance(n, ast.ClassDef)
                     and n.name == classname), None)
                if scope is None:
                    yield Finding(
                        "lock-discipline", disp, 1,
                        f"class {classname} not found but registered in "
                        "LOCK_GUARDED — fix the registry or the rename")
                    continue
            else:
                scope = m.tree
            # guarded attrs must exist at all — a registry entry for a
            # deleted attribute is a stale contract
            found_any = {a: False for a in attrs}
            for fn in ast.walk(scope):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__" or fn.name.startswith("_init"):
                    for n in ast.walk(fn):
                        a = _guarded_access(n, attrs, classname is not None)
                        if a:
                            found_any[a] = True
                    continue
                yield from _check_fn(fn, disp, classname is not None,
                                     lock, attrs, found_any)
            for attr, seen in sorted(found_any.items()):
                if not seen:
                    yield Finding(
                        "lock-discipline", disp, 1,
                        f"LOCK_GUARDED names {attr!r} in "
                        f"{classname or 'module scope'} but no such "
                        "access exists — stale registry entry")


def _guarded_access(node, attrs: frozenset, classscope: bool) \
        -> Optional[str]:
    if classscope:
        if isinstance(node, ast.Attribute) and node.attr in attrs \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
    else:
        if isinstance(node, ast.Name) and node.id in attrs:
            return node.id
    return None


def _check_fn(fn, disp: str, classscope: bool, lock: str,
              attrs: frozenset, found_any: dict) -> Iterable[Finding]:
    def visit(node, held: bool):
        if isinstance(node, ast.With):
            now_held = held or lock in _with_locks(node, classscope)
            for item in node.items:
                yield from visit(item.context_expr, held)
                if item.optional_vars is not None:
                    yield from visit(item.optional_vars, held)
            for child in node.body:
                yield from visit(child, now_held)
            return
        a = _guarded_access(node, attrs, classscope)
        if a is not None:
            found_any[a] = True
            if not held:
                target = f"self.{a}" if classscope else a
                lockname = f"self.{lock}" if classscope else lock
                yield Finding(
                    "lock-discipline", disp, node.lineno,
                    f"{target} accessed outside `with {lockname}:` "
                    f"in {fn.name}()")
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs run later, possibly on another thread —
                # they are separate scopes the outer walk visits on
                # their own (with no lock held) and must lock themselves
                continue
            if isinstance(child, ast.Lambda):
                # a lambda body runs later too (collector callbacks are
                # the canonical case) — but unlike a def it CANNOT take
                # the lock itself, so any guarded access inside one is
                # a finding regardless of what the definition site held
                yield from visit(child.body, False)
                continue
            yield from visit(child, held)

    for stmt in fn.body:
        yield from visit(stmt, False)


# -- no-blocking-on-loop ---------------------------------------------------

# modules whose async defs run on a serving event loop
_LOOP_SCOPES = ("data/api/", "workflow/create_server.py")

# known-blocking stdlib calls: receiver-qualified names
_BLOCKING_QUALIFIED = {
    ("time", "sleep"), ("os", "fsync"), ("os", "fdatasync"),
    ("os", "system"), ("os", "listdir"), ("os", "scandir"),
    ("os", "replace"), ("os", "rename"), ("os", "unlink"),
    ("os", "makedirs"), ("os", "walk"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"), ("fcntl", "flock"),
    ("shutil", "copy"), ("shutil", "copyfile"), ("shutil", "move"),
    ("shutil", "rmtree"),
}
# bare names (builtins / common from-imports)
_BLOCKING_BARE = {"open", "urlopen"}


@rule("no-blocking-on-loop",
      "no blocking stdlib calls (time.sleep, open, fsync, subprocess, "
      "urlopen, os.listdir, ...) inside async def bodies of the serving "
      "modules — a blocked loop freezes every connection on that server")
def no_blocking_on_loop(project: Project) -> Iterable[Finding]:
    mods = []
    for scope in _LOOP_SCOPES:
        if scope.endswith(".py"):
            m = project.module(scope)
            if m is not None:
                mods.append(m)
        else:
            mods.extend(project.modules(scope))
    for m in mods:
        if m.tree is None:
            continue
        disp = project.display_path(m)
        for afn in m.walk():
            if not isinstance(afn, ast.AsyncFunctionDef):
                continue
            stack = list(afn.body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.Lambda,
                                  ast.AsyncFunctionDef)):
                    # nested sync defs are usually shipped to executors
                    # (to_thread/run_in_executor); nested async defs are
                    # visited by the outer module walk
                    continue
                stack.extend(ast.iter_child_nodes(n))
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                if isinstance(f, ast.Name) and f.id in _BLOCKING_BARE:
                    yield Finding(
                        "no-blocking-on-loop", disp, n.lineno,
                        f"blocking call {f.id}() inside async "
                        f"{afn.name}() — move it off-loop "
                        "(asyncio.to_thread / run_in_executor)")
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name):
                    recv = f.value.id.lstrip("_")
                    if (recv, f.attr) in _BLOCKING_QUALIFIED:
                        yield Finding(
                            "no-blocking-on-loop", disp, n.lineno,
                            f"blocking call {f.value.id}.{f.attr}() "
                            f"inside async {afn.name}() — move it "
                            "off-loop (asyncio.to_thread / "
                            "run_in_executor)")


RULES = [lock_discipline, no_blocking_on_loop]
