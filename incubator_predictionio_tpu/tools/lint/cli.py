"""``pio lint`` CLI: exit 1 on findings, ``--json`` for machines.

Kept jax-free and imported lazily by the console so linting a broken
tree costs a parse pass, not a backend initialization."""

from __future__ import annotations

import argparse
import sys

from . import ALL_RULES, Project, report_json, run_lint


def main(args: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="repo-wide static analysis: concurrency/convention "
                    "rules over one AST parse pass "
                    "(docs/operations.md 'Static analysis')")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME[,NAME...]",
                   help="run only these rules (repeatable, comma-ok); "
                        "skips the unused-suppression check")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    ns = p.parse_args(args)

    if ns.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:<24} {r.rationale}")
        return 0

    only = None
    if ns.rule:
        only = [n.strip() for chunk in ns.rule for n in chunk.split(",")
                if n.strip()]
        if not only:
            # `--rule ""` selecting nothing must not report "clean"
            print("pio lint: --rule selected no rules", file=sys.stderr)
            return 2
    try:
        result = run_lint(Project.from_repo(ns.root), ALL_RULES, only=only)
    except ValueError as e:  # unknown --rule name
        print(f"pio lint: {e}", file=sys.stderr)
        return 2

    if ns.json:
        print(report_json(result))
    else:
        for f in result["findings"]:
            print(f.render())
        n = len(result["findings"])
        status = "clean" if n == 0 else f"{n} finding(s)"
        print(f"pio lint: {status} — {len(result['rules'])} rule(s) over "
              f"{result['modules']} module(s), "
              f"{result['suppressed']} suppression(s) honoured",
              file=sys.stderr)
    return 1 if result["findings"] else 0
