"""``pio lint`` CLI: exit 1 on findings, ``--json`` for machines.

Kept jax-free and imported lazily by the console so linting a broken
tree costs a parse pass, not a backend initialization.

``--changed [REF]`` is the incremental mode (pre-commit hooks, big
refactors): the WHOLE-program analysis still runs — a call-graph rule
cannot be correct on a file subset — but findings are reported only
into modules (and docs files) that differ from ``REF`` (default
``HEAD``, untracked files included). ``--profile`` prints per-rule
wall time so a rule that starts eating the tier-1 budget is named, not
guessed at."""

from __future__ import annotations

import argparse
import subprocess
import sys

from . import ALL_RULES, Project, report_json, run_lint


def _changed_paths(repo_root: str, ref: str) -> set:
    """repo_root-relative paths differing from ``ref`` (tracked diff +
    untracked files). Git reports paths relative to its TOPLEVEL, which
    is not necessarily the lint root (a repo nested in a larger
    checkout) — re-anchor them or the filter silently drops every
    finding and reports a false "clean". Raises ValueError with git's
    own words when the ref is unusable."""
    import pathlib

    def git(*args: str) -> str:
        proc = subprocess.run(
            ["git", "-C", repo_root, *args],
            capture_output=True, text=True, timeout=60)
        if proc.returncode != 0:
            raise ValueError(proc.stderr.strip()
                             or f"git {' '.join(args)} failed")
        return proc.stdout

    toplevel = pathlib.Path(git("rev-parse", "--show-toplevel").strip())
    prefix = pathlib.Path(repo_root).resolve().relative_to(
        toplevel).as_posix()
    prefix = "" if prefix == "." else prefix + "/"

    changed = set()
    # --full-name: ls-files is cwd-relative from a subdirectory while
    # diff is toplevel-relative — force both onto toplevel paths
    for args in (("diff", "--name-only", "-z", ref, "--"),
                 ("ls-files", "--others", "--exclude-standard",
                  "--full-name", "-z")):
        for chunk in git(*args).split("\0"):
            if chunk.startswith(prefix):
                changed.add(chunk[len(prefix):])
    changed.discard("")
    return changed


def main(args: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="repo-wide static analysis: concurrency/convention/"
                    "flow rules over one AST parse pass "
                    "(docs/operations.md 'Static analysis')")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME[,NAME...]",
                   help="run only these rules (repeatable, comma-ok); "
                        "skips the unused-suppression check")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="report findings only in files differing from "
                        "REF (default HEAD; untracked included) — the "
                        "whole-program rules still see the full repo")
    p.add_argument("--profile", action="store_true",
                   help="print per-rule wall time to stderr")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: this checkout)")
    ns = p.parse_args(args)

    if ns.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:<28} {r.rationale}")
        return 0

    only = None
    if ns.rule:
        only = [n.strip() for chunk in ns.rule for n in chunk.split(",")
                if n.strip()]
        if not only:
            # `--rule ""` selecting nothing must not report "clean"
            print("pio lint: --rule selected no rules", file=sys.stderr)
            return 2
    project = Project.from_repo(ns.root)
    changed = None
    if ns.changed is not None:
        try:
            changed = _changed_paths(str(project.repo_root), ns.changed)
        except (ValueError, OSError) as e:
            print(f"pio lint: --changed {ns.changed}: {e}",
                  file=sys.stderr)
            return 2
    try:
        result = run_lint(project, ALL_RULES, only=only)
    except ValueError as e:  # unknown --rule name
        print(f"pio lint: {e}", file=sys.stderr)
        return 2

    findings = result["findings"]
    scope = ""
    if changed is not None:
        findings = [f for f in findings if f.path in changed]
        scope = f", scoped to {len(changed)} changed file(s)"

    if ns.profile:
        for name, secs in sorted(result["timings"],
                                 key=lambda t: -t[1]):
            print(f"pio lint: {name:<28} {secs * 1e3:8.1f} ms",
                  file=sys.stderr)

    if ns.json:
        print(report_json({**result, "findings": findings}))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        status = "clean" if n == 0 else f"{n} finding(s)"
        print(f"pio lint: {status} — {len(result['rules'])} rule(s) over "
              f"{result['modules']} module(s), "
              f"{result['suppressed']} suppression(s) honoured{scope}",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":   # pragma: no cover — the pre-commit hook
    sys.exit(main(sys.argv[1:]))
