"""Multi-host initialization — the control-plane analog of Spark's
driver/executor RPC (reference dependency; SURVEY.md §2.10).

Single-host: no-op. Multi-host: `jax.distributed.initialize` connects every
host to the coordination service over DCN; afterwards jax.devices() spans
the pod and the same mesh/pjit code runs unchanged (single-controller SPMD
per host — the workflow binary is simply launched once per host, the way
the reference launches one executor JVM per node).

Failure semantics (the gang supervisor depends on these): a worker that
cannot REACH its coordinator must error within ``PIO_COORDINATOR_TIMEOUT_MS``
instead of retrying forever, and a worker whose coordinator DIES mid-run
must notice within ``PIO_DIST_HEARTBEAT_MS × PIO_DIST_MAX_MISSING_HEARTBEATS``
(the coordination-service health check, which also tears down the
remaining processes when any peer is declared dead) — so a dead gang
member surfaces as a worker error the supervisor can act on rather than
an infinite hang in the next collective.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from ..common import envknobs

log = logging.getLogger("pio.distributed")


def process_count() -> int:
    return jax.process_count()


def is_multi_host() -> bool:
    return jax.process_count() > 1


def resolve_distributed_timeouts() -> dict:
    """Resolved connection/health-check knobs (seconds, jax's unit).

    - ``PIO_COORDINATOR_TIMEOUT_MS`` — how long a process retries the
      initial coordinator connection before erroring (jax
      ``initialization_timeout``; default 300 s). Floored at 1 s —
      jax takes whole seconds.
    - ``PIO_DIST_HEARTBEAT_MS`` — coordination-service heartbeat
      interval, client and service side (default 10 s, floor 1 s).
    - ``PIO_DIST_MAX_MISSING_HEARTBEATS`` — missed beats before a
      process is declared dead and the job torn down (default 10).

    Malformed or absent values fall back to the jax defaults (a typo'd
    knob must not take down a training job at init).
    """
    init_s = envknobs.env_ms("PIO_COORDINATOR_TIMEOUT_MS", 300_000.0,
                             lo_ms=1000.0)
    hb_s = envknobs.env_ms("PIO_DIST_HEARTBEAT_MS", 10_000.0, lo_ms=1000.0)
    missing = envknobs.env_int("PIO_DIST_MAX_MISSING_HEARTBEATS", 10, lo=2)
    return {
        "initialization_timeout": max(1, int(round(init_s))),
        "heartbeat_interval": max(1, int(round(hb_s))),
        "max_missing_heartbeats": missing,
    }


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize JAX multi-controller runtime from args or PIO_* env vars
    (PIO_COORDINATOR_ADDRESS, PIO_NUM_PROCESSES, PIO_PROCESS_ID). Safe to
    call when unset → single-process mode. Timeout/health-check knobs:
    :func:`resolve_distributed_timeouts`."""
    coordinator_address = (
        coordinator_address
        or envknobs.env_str("PIO_COORDINATOR_ADDRESS", "", lower=False))
    if not coordinator_address:
        log.debug("single-process mode (no PIO_COORDINATOR_ADDRESS)")
        return
    # identity knobs parse STRICTLY (int() raises on garbage AND on a
    # set-but-empty value): a gang worker whose rank/world-size env is
    # garbled must crash loudly at startup — any tolerant fallback to
    # rank 0 / world 1 would collide with the real leader or hang its
    # peers' collectives instead
    num_processes = num_processes or int(
        os.environ.get("PIO_NUM_PROCESSES", "1"))  # pio-lint: disable=knob-envknobs -- identity knob: strict crash beats tolerant world=1
    process_id = (process_id if process_id is not None
                  else int(os.environ.get("PIO_PROCESS_ID", "0")))  # pio-lint: disable=knob-envknobs -- identity knob: strict crash beats tolerant rank=0
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # The CPU PJRT client ships WITHOUT cross-process collectives by
        # default ("Multiprocess computations aren't implemented on the
        # CPU backend") — select the gloo TCP implementation before the
        # backend initializes. TPU/GPU pods use their own interconnect
        # collectives and never read this flag.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older/newer jax: no flag
            log.debug("jax_cpu_collectives_implementation not supported")
    t = resolve_distributed_timeouts()
    try:
        # The public jax.distributed.initialize does not expose the
        # coordination-service heartbeat knobs (jax 0.4.x); it is a thin
        # wrapper over State.initialize plus this same guard, so call
        # the state object directly and keep the guard.
        from jax._src import distributed as _dist
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            raise RuntimeError(
                "initialize_distributed() must be called before any JAX "
                "computations are executed.")
        _dist.global_state.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=t["initialization_timeout"],
            service_heartbeat_interval_seconds=t["heartbeat_interval"],
            service_max_missing_heartbeats=t["max_missing_heartbeats"],
            client_heartbeat_interval_seconds=t["heartbeat_interval"],
            client_max_missing_heartbeats=t["max_missing_heartbeats"],
        )
    except (ImportError, TypeError, AttributeError):
        # Private surface moved (newer jax): the public API still honors
        # the connection timeout; heartbeat cadence stays at defaults.
        log.debug("falling back to public jax.distributed.initialize",
                  exc_info=True)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=t["initialization_timeout"],
        )
    log.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        process_id, num_processes, len(jax.devices()),
    )
