"""Multi-host initialization — the control-plane analog of Spark's
driver/executor RPC (reference dependency; SURVEY.md §2.10).

Single-host: no-op. Multi-host: `jax.distributed.initialize` connects every
host to the coordination service over DCN; afterwards jax.devices() spans
the pod and the same mesh/pjit code runs unchanged (single-controller SPMD
per host — the workflow binary is simply launched once per host, the way
the reference launches one executor JVM per node).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("pio.distributed")


def process_count() -> int:
    return jax.process_count()


def is_multi_host() -> bool:
    return jax.process_count() > 1


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize JAX multi-controller runtime from args or PIO_* env vars
    (PIO_COORDINATOR_ADDRESS, PIO_NUM_PROCESSES, PIO_PROCESS_ID). Safe to
    call when unset → single-process mode."""
    coordinator_address = coordinator_address or os.environ.get("PIO_COORDINATOR_ADDRESS")
    if not coordinator_address:
        log.debug("single-process mode (no PIO_COORDINATOR_ADDRESS)")
        return
    num_processes = num_processes or int(os.environ.get("PIO_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(os.environ.get("PIO_PROCESS_ID", "0"))
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # The CPU PJRT client ships WITHOUT cross-process collectives by
        # default ("Multiprocess computations aren't implemented on the
        # CPU backend") — select the gloo TCP implementation before the
        # backend initializes. TPU/GPU pods use their own interconnect
        # collectives and never read this flag.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):  # older/newer jax: no flag
            log.debug("jax_cpu_collectives_implementation not supported")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        process_id, num_processes, len(jax.devices()),
    )
