"""Mesh/sharding/collective helpers — the TPU-native replacement for the
reference's Spark cluster + shuffle layer (SURVEY.md §2.9-2.10)."""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    default_mesh,
    local_device_count,
    mesh_from_devices,
    replicated,
    shard_rows,
    with_mesh,
)
from .distributed import initialize_distributed, is_multi_host, process_count

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "default_mesh", "initialize_distributed",
    "is_multi_host", "local_device_count", "mesh_from_devices",
    "process_count", "replicated", "shard_rows", "with_mesh",
]
