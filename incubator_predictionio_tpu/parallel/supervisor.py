"""Gang supervision for multi-worker training — worker liveness,
heartbeats, and checkpoint gang-restart.

Production SPMD training treats worker death as routine: every process
participates in every collective, so ONE dead or wedged worker leaves the
survivors blocked in the next all-reduce forever. The recovery model is
the TensorFlow one (arxiv 1605.08695) — supervise the gang, and on any
failure kill ALL of it and relaunch from the last checkpoint — rather
than lineage recomputation. This module is that supervisor:

- :class:`Supervisor` spawns N worker processes with the
  coordinator/process-id env wiring (``PIO_COORDINATOR_ADDRESS``,
  ``PIO_NUM_PROCESSES``, ``PIO_PROCESS_ID``), watches process liveness
  AND per-worker heartbeat files, and on a nonzero exit, worker death,
  or heartbeat stall kills the whole gang and relaunches it with
  ``--resume`` — bounded by ``PIO_TRAIN_MAX_RESTARTS`` with jittered
  exponential backoff (common/resilience.RetryPolicy). SIGTERM on the
  supervisor drains the gang cleanly instead (workers checkpoint at the
  next sweep boundary and exit; the run stays ``--resume``-able).
- Workers call :func:`beat` between ALS sweeps (hooked in ``ops/als.py``
  and ``workflow/core_workflow.py``): a cheap mtime touch of
  ``PIO_WORKER_HEARTBEAT_FILE``. A worker that is alive-but-wedged
  (SIGSTOP, deadlocked collective, hung storage read) stops beating and
  the stall detector catches what ``poll()`` cannot.
- Drain is collective: :func:`drain_requested_global` allgathers the
  local SIGTERM flag across the gang at each sweep boundary, so every
  process takes the drain branch at the SAME iteration and the
  checkpoint barrier cannot deadlock against a peer that missed the
  signal by one sweep.

Telemetry (PR 4 registry): ``pio_train_restarts_total{reason}``,
``pio_train_worker_alive{worker}``,
``pio_train_worker_heartbeat_age_seconds{worker}``,
``pio_train_gang_state``. The same numbers (plus an event log with
timestamps — what the gang bench bracket reads) are mirrored to
``<run_dir>/supervisor.json`` so a foreign process can watch a live gang.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Optional, Sequence

from ..common import envknobs, telemetry

log = logging.getLogger("pio.supervisor")

__all__ = [
    "GangConfig", "GangDrainRequested", "Supervisor", "beat",
    "beat_while", "drain_requested", "drain_requested_global",
    "gang_active", "install_worker_signal_handlers", "request_drain",
    "reset_drain",
]

# env the supervisor sets on every worker
ENV_HEARTBEAT_FILE = "PIO_WORKER_HEARTBEAT_FILE"
ENV_GANG_WORKER = "PIO_GANG_WORKER"
ENV_GANG_INSTANCE_ID = "PIO_GANG_INSTANCE_ID"

# terminal states Supervisor.run() can land in
COMPLETED, DRAINED, FAILED = "completed", "drained", "failed"

#: exit code of a worker that checkpointed and exited at a drain request
#: (GangDrainRequested). NOT a failure: a worker can be drained without
#: the supervisor's stop flag being set (operator SIGTERMs a worker
#: directly — the allgathered flag drains the whole gang), and restarting
#: a run the operator just stopped would burn the restart budget on
#: exactly the wrong thing.
DRAIN_EXIT_CODE = 3


# ---------------------------------------------------------------------------
# worker-side hooks (heartbeat + drain flag)
# ---------------------------------------------------------------------------

_hb_lock = threading.Lock()
_hb_last = 0.0
_hb_interval: Optional[float] = None
_drain_event = threading.Event()


def gang_active() -> bool:
    """True inside a supervised training worker."""
    return os.environ.get(ENV_GANG_WORKER) == "1"


def beat() -> None:
    """Touch this worker's heartbeat file (no-op outside a gang).

    Called between training sweeps; throttled to half the configured
    heartbeat interval so a microsecond-sweep loop doesn't turn into an
    utime storm. The file is created on the first call — the supervisor
    treats creation as 'worker reached the training loop' and only then
    arms the stall detector.
    """
    path = os.environ.get(ENV_HEARTBEAT_FILE)
    if not path:
        return
    global _hb_last, _hb_interval
    now = time.monotonic()
    with _hb_lock:
        if _hb_interval is None:
            _hb_interval = max(
                0.01, envknobs.env_ms("PIO_WORKER_HEARTBEAT_MS", 1000.0,
                                      lo_ms=20.0) / 2.0)
        if now - _hb_last < _hb_interval:
            return
        _hb_last = now
    try:
        with open(path, "a"):
            pass
        os.utime(path, None)
    except OSError:  # heartbeat dir vanished: the supervisor is gone
        log.debug("heartbeat touch failed for %s", path, exc_info=True)


class beat_while:
    """Context manager: background thread beats every ``interval`` while
    the body runs. For phases with no natural beat points — the gang
    leader's model persistence (device_get + pickle + storage insert can
    dwarf the stall threshold at scale, and a training job whose TRAINING
    succeeded must not be gang-killed while saving the result). Storage
    hangs inside the block are not masked forever: egress runs under
    resilience retry/deadline budgets, and the supervisor's drain SIGKILL
    remains the backstop. No-op outside a gang."""

    def __init__(self, interval: float = 5.0):
        self.interval = interval
        self._stop: Optional[threading.Event] = None
        self._t: Optional[threading.Thread] = None

    def __enter__(self):
        if not os.environ.get(ENV_HEARTBEAT_FILE):
            return self
        self._stop = threading.Event()

        def _pump(stop):
            while not stop.wait(self.interval):
                beat()

        self._t = threading.Thread(
            target=_pump, args=(self._stop,), daemon=True)
        self._t.start()
        return self

    def __exit__(self, *exc):
        if self._stop is not None:
            self._stop.set()
            self._t.join(timeout=5)
        return False


def request_drain(signum=None, frame=None) -> None:
    """SIGTERM handler body: ask the training loop to checkpoint and
    exit at the next sweep boundary."""
    _drain_event.set()


def drain_requested() -> bool:
    return _drain_event.is_set()


def reset_drain() -> None:
    _drain_event.clear()


def drain_requested_global() -> bool:
    """Gang-consistent drain flag, checked between sweeps.

    Multi-process gangs allgather the local flag so every process sees
    the SAME answer at the SAME sweep boundary — otherwise the process
    that caught SIGTERM a sweep earlier would enter the checkpoint
    barrier while its peers enter the next training collective, and the
    gang would deadlock (the supervisor's drain deadline would SIGKILL
    it, losing the drain checkpoint). Single-process runs read the local
    flag directly; non-gang runs never pay the collective.
    """
    if not gang_active():
        return _drain_event.is_set()
    import jax

    if jax.process_count() <= 1:
        return _drain_event.is_set()
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.int32(1 if _drain_event.is_set() else 0))
    return bool(np.asarray(flags).max())


def install_worker_signal_handlers() -> None:
    """Route SIGTERM (and SIGINT, which the supervisor's process group
    forwards on Ctrl-C) to the drain flag instead of killing the worker
    mid-sweep. Main-thread only — signal.signal requires it."""
    signal.signal(signal.SIGTERM, request_drain)
    signal.signal(signal.SIGINT, request_drain)


class GangDrainRequested(Exception):
    """Raised by a training loop after it checkpointed at a drain
    request; the worker exits and the supervisor stops without
    restarting (the run resumes later with ``--resume``)."""

    def __init__(self, step: int):
        super().__init__(f"gang drain requested; checkpointed at step {step}")
        self.step = int(step)


# ---------------------------------------------------------------------------
# supervisor config
# ---------------------------------------------------------------------------

class GangConfig:
    """Resolved supervision knobs (all overridable via environment).

    - ``PIO_NUM_WORKERS`` — gang size (``pio train --num-workers`` wins)
    - ``PIO_WORKER_HEARTBEAT_MS`` — worker touch cadence (default 1s)
    - ``PIO_WORKER_STALL_MS`` — heartbeat age that declares a live
      process wedged (default 120s: stalls are judged against sweep
      cadence, and a saturated host can stretch a sweep a lot further
      than it can stretch a poll)
    - ``PIO_WORKER_INIT_GRACE_MS`` — budget from spawn to FIRST beat
      (default 600s: covers jax.distributed init + XLA compile, which
      beat nothing)
    - ``PIO_TRAIN_MAX_RESTARTS`` — gang relaunch budget (default 3)
    - ``PIO_TRAIN_DRAIN_MS`` — SIGTERM→SIGKILL grace during drain
      (default 30s)
    - ``PIO_SUPERVISOR_POLL_MS`` — monitor cadence (default 200ms)
    """

    __slots__ = ("num_workers", "heartbeat_ms", "stall_ms", "init_grace_ms",
                 "max_restarts", "drain_ms", "poll_ms")

    def __init__(self, num_workers: int = 1, heartbeat_ms: float = 1000.0,
                 stall_ms: float = 120_000.0, init_grace_ms: float = 600_000.0,
                 max_restarts: int = 3, drain_ms: float = 30_000.0,
                 poll_ms: float = 200.0):
        self.num_workers = max(1, int(num_workers))
        self.heartbeat_ms = max(20.0, float(heartbeat_ms))
        self.stall_ms = max(self.heartbeat_ms * 2, float(stall_ms))
        self.init_grace_ms = max(self.stall_ms, float(init_grace_ms))
        self.max_restarts = max(0, int(max_restarts))
        self.drain_ms = max(0.0, float(drain_ms))
        self.poll_ms = min(max(10.0, float(poll_ms)), self.heartbeat_ms)

    @classmethod
    def from_env(cls, num_workers: Optional[int] = None) -> "GangConfig":
        return cls(
            num_workers=(num_workers if num_workers is not None
                         else envknobs.env_int("PIO_NUM_WORKERS", 1, lo=1)),
            heartbeat_ms=envknobs.env_float(
                "PIO_WORKER_HEARTBEAT_MS", 1000.0, lo=20.0),
            stall_ms=envknobs.env_float(
                "PIO_WORKER_STALL_MS", 120_000.0, lo=100.0),
            init_grace_ms=envknobs.env_float(
                "PIO_WORKER_INIT_GRACE_MS", 600_000.0, lo=1000.0),
            max_restarts=envknobs.env_int(
                "PIO_TRAIN_MAX_RESTARTS", 3, lo=0),
            drain_ms=envknobs.env_float(
                "PIO_TRAIN_DRAIN_MS", 30_000.0, lo=0.0),
            poll_ms=envknobs.env_float(
                "PIO_SUPERVISOR_POLL_MS", 200.0, lo=10.0),
        )

    def to_json(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}


# ---------------------------------------------------------------------------
# telemetry (process-wide; created lazily so importing this module costs
# nothing in processes that never supervise)
# ---------------------------------------------------------------------------

def _metrics():
    reg = telemetry.registry()
    return (
        reg.counter("pio_train_restarts_total",
                    "Gang restarts by failure reason", ("reason",)),
        reg.gauge("pio_train_worker_alive",
                  "1 while the worker process is running", ("worker",)),
        reg.gauge("pio_train_worker_heartbeat_age_seconds",
                  "Seconds since the worker last touched its heartbeat file",
                  ("worker",)),
        reg.gauge("pio_train_gang_state",
                  "0 idle, 1 running, 2 draining, 3 failed").labels(),
    )


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = ("idx", "proc", "hb_path", "log_path", "spawned_at",
                 "hb_token", "hb_seen_at")

    def __init__(self, idx, proc, hb_path, log_path, spawned_at):
        self.idx = idx
        self.proc = proc
        self.hb_path = hb_path
        self.log_path = log_path
        self.spawned_at = spawned_at
        # mtime is only an opaque CHANGE token; ages are measured on the
        # monotonic clock from when the change was observed, so an NTP
        # step can neither spuriously stall a healthy gang nor hide a
        # genuinely wedged worker.
        self.hb_token = None
        self.hb_seen_at = None

    def heartbeat_age_ms(self) -> Optional[float]:
        """Monotonic ms since the last observed beat, or None before the
        first one (the init grace window covers distributed init +
        compile)."""
        try:
            token = os.stat(self.hb_path).st_mtime_ns
        except OSError:
            return None
        now = time.monotonic()
        if token != self.hb_token:
            self.hb_token = token
            self.hb_seen_at = now
        return max(0.0, (now - self.hb_seen_at) * 1000.0)


class Supervisor:
    """Launch and babysit one training gang until it completes, drains,
    or exhausts its restart budget.

    ``worker_argv`` is the full command line of ONE worker; the
    supervisor adds only environment (coordinator wiring, heartbeat
    file, gang marker) and — on restart attempts — ``resume_argv`` so
    the relaunched gang continues from the latest checkpoint.

    ``per_worker_env`` (worker idx → env overrides) applies to the
    FIRST launch only: it exists to arm per-worker chaos
    (``PIO_FAULT_SPEC`` crash/latency rules) and a restarted gang must
    come up clean or the same injected fault would kill every relaunch.
    Pass a callable ``(attempt, worker_idx) -> dict`` to control every
    attempt explicitly.

    This class is the ONLY sanctioned spawner of training worker
    processes (guard-tested, like the ingest buffer's single dispatch
    path): liveness, restart accounting, and drain semantics all assume
    every gang member is on the supervisor's books.
    """

    def __init__(self, worker_argv: Sequence[str],
                 num_workers: Optional[int] = None, *,
                 env: Optional[dict] = None,
                 per_worker_env=None,
                 config: Optional[GangConfig] = None,
                 run_dir: Optional[str] = None,
                 gang_instance_id: Optional[str] = None,
                 resume_argv: Sequence[str] = ("--resume",),
                 coordinator_host: str = "127.0.0.1",
                 wire_coordinator: bool = True,
                 restart_scope: str = "gang"):
        """``wire_coordinator=False`` skips the jax.distributed env
        (``PIO_COORDINATOR_ADDRESS`` + the per-attempt port): the
        workers are independent servers, not an SPMD gang.

        ``restart_scope`` selects the recovery model. ``"gang"`` (the
        training default): every process participates in every
        collective, so ONE failure kills and relaunches ALL of them
        from the checkpoint. ``"worker"`` (services — the partitioned
        event server): workers share nothing at runtime, so a dead or
        wedged worker is killed and relaunched INDIVIDUALLY (its
        startup replays its own WAL partition) while the rest keep
        serving; ``max_restarts`` is a per-worker budget, and ANY exit
        — including rc 0 — is a failure, because a service worker has
        no legitimate reason to stop while supervised."""
        if restart_scope not in ("gang", "worker"):
            raise ValueError(f"restart_scope {restart_scope!r}")
        self.worker_argv = list(worker_argv)
        self.config = config or GangConfig.from_env(num_workers)
        if num_workers is not None:
            self.config.num_workers = max(1, int(num_workers))
        self.wire_coordinator = wire_coordinator
        self.restart_scope = restart_scope
        self.base_env = dict(os.environ if env is None else env)
        if callable(per_worker_env):
            self._env_for = per_worker_env
        else:
            first = {int(k): dict(v) for k, v in (per_worker_env or {}).items()}
            self._env_for = lambda attempt, idx: (
                first.get(idx, {}) if attempt == 0 else {})
        self.run_dir = run_dir or self._default_run_dir(gang_instance_id)
        self.gang_instance_id = gang_instance_id
        self.resume_argv = list(resume_argv)
        self.coordinator_host = coordinator_host

        self.restarts = 0
        # per-worker relaunch counts (restart_scope="worker"): read by
        # the fleet front's /healthz aggregation and mirrored into
        # supervisor.json; gang-scope restarts stay in self.restarts
        self.worker_restarts = [0] * self.config.num_workers
        self.state = "idle"
        self.events: list[dict] = []
        self._workers: list[_Worker] = []
        self._stop = threading.Event()
        self._attempt = 0
        # dynamic membership (restart_scope="worker" only): add/retire
        # requests land here from any thread and are applied by the
        # supervision loop itself, so every spawn happens on the
        # SUPERVISOR thread — pdeathsig binds to the spawning thread,
        # and a late-added worker must share the initial workers'
        # parent-death contract, not a shorter-lived caller's
        self._membership_lock = threading.Lock()
        self._membership_cmds: list[tuple[str, int]] = []
        # idx -> monotonic SIGKILL deadline; a retiring worker is
        # EXPECTED to exit, so the any-exit-is-failure service rule and
        # the stall detector both skip it
        self._retiring: dict[int, float] = {}
        os.makedirs(self.run_dir, exist_ok=True)

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _default_run_dir(gang_id: Optional[str]) -> str:
        from ..data.storage.registry import base_dir

        return os.path.join(base_dir(), "gang", gang_id or f"pid{os.getpid()}")

    @staticmethod
    def _free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def request_stop(self, signum=None, frame=None) -> None:
        """SIGTERM entry: drain the gang and stop (no restart)."""
        self._stop.set()

    def install_signal_handlers(self) -> None:
        """Main-thread only (CLI path; tests call request_stop())."""
        signal.signal(signal.SIGTERM, self.request_stop)
        signal.signal(signal.SIGINT, self.request_stop)

    def _event(self, type_: str, **kw) -> None:
        self.events.append({"type": type_, "t": time.time(), **kw})

    def worker_pids(self) -> list[Optional[int]]:
        return [w.proc.pid if w.proc.poll() is None else None
                for w in self._workers]

    def _worker_by_idx(self, idx: int) -> Optional[_Worker]:
        for w in self._workers:
            if w.idx == idx:
                return w
        return None

    def worker_pid(self, idx: int) -> Optional[int]:
        """Keyed pid lookup — positional ``worker_pids()`` stops being
        meaningful once dynamic membership leaves index gaps."""
        w = self._worker_by_idx(idx)
        if w is None or w.proc.poll() is not None:
            return None
        return w.proc.pid

    def live_worker_indices(self) -> list[int]:
        """Indices on the books and not mid-retirement."""
        return sorted(w.idx for w in self._workers
                      if w.idx not in self._retiring)

    def is_retiring(self, idx: int) -> bool:
        return idx in self._retiring

    # -- dynamic membership (service scope) --------------------------------

    def add_worker(self, idx: Optional[int] = None) -> int:
        """Enqueue a NEW service worker at slot ``idx`` (lowest free
        slot when None); returns the slot. The spawn itself happens on
        the supervision thread at its next sweep — same heartbeat
        registration, restart budget, and parent-death arming as a
        launch-time worker. Thread-safe; ``restart_scope='worker'``
        only (a gang's size is its collective's world size)."""
        if self.restart_scope != "worker":
            raise RuntimeError("dynamic membership requires "
                               "restart_scope='worker'")
        with self._membership_lock:
            taken = {w.idx for w in self._workers}
            taken.update(i for op, i in self._membership_cmds
                         if op == "add")
            if idx is None:
                idx = 0
                while idx in taken:
                    idx += 1
            elif idx in taken:
                raise ValueError(f"worker {idx} is already on the books")
            self._membership_cmds.append(("add", int(idx)))
        return int(idx)

    def retire_worker(self, idx: int) -> None:
        """Enqueue a graceful retirement of worker ``idx``: the
        supervision thread SIGTERMs it (the worker's normal drain
        path), exempts it from failure detection, and books it out
        when it exits — SIGKILL only past the drain budget. Thread-
        safe; ``restart_scope='worker'`` only."""
        if self.restart_scope != "worker":
            raise RuntimeError("dynamic membership requires "
                               "restart_scope='worker'")
        with self._membership_lock:
            self._membership_cmds.append(("retire", int(idx)))

    def _apply_membership(self) -> None:
        """Drain queued add/retire commands (supervision thread)."""
        with self._membership_lock:
            cmds, self._membership_cmds = self._membership_cmds, []
        for op, idx in cmds:
            if op == "add":
                if self._worker_by_idx(idx) is not None:
                    continue  # raced a concurrent add of the same slot
                while len(self.worker_restarts) <= idx:
                    self.worker_restarts.append(0)
                self._workers.append(
                    self._spawn_worker(idx, None, resume=False, attempt=0))
                self._event("workerAdded", worker=idx)
                log.info("service worker %d added (now %d on the books)",
                         idx, len(self._workers))
            else:
                w = self._worker_by_idx(idx)
                if w is None or idx in self._retiring:
                    continue
                self._retiring[idx] = (time.monotonic()
                                       + self.config.drain_ms / 1000.0)
                if w.proc.poll() is None:
                    try:
                        w.proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
                self._event("workerRetireStart", worker=idx)
                log.info("service worker %d retiring (drain budget "
                         "%.1fs)", idx, self.config.drain_ms / 1000.0)

    def _reap_retiring(self) -> None:
        """Book out retiring workers that exited; SIGKILL past the
        drain deadline (supervision thread)."""
        if not self._retiring:
            return
        now = time.monotonic()
        for idx in list(self._retiring):
            w = self._worker_by_idx(idx)
            if w is None:
                del self._retiring[idx]
                continue
            rc = w.proc.poll()
            if rc is None and now >= self._retiring[idx]:
                try:
                    w.proc.kill()
                except OSError:
                    pass
                w.proc.wait()
                rc = w.proc.poll()
            if rc is not None:
                self._workers.remove(w)
                del self._retiring[idx]
                self._event("workerRetired", worker=idx, rc=rc)
                log.info("service worker %d retired (rc %s, %d still "
                         "on the books)", idx, rc, len(self._workers))

    # -- gang lifecycle ----------------------------------------------------

    def _spawn_worker(self, i: int, port: Optional[int],
                      resume: bool, attempt: int) -> _Worker:
        cfg = self.config
        argv = list(self.worker_argv)
        if resume:
            for tok in self.resume_argv:
                if tok not in argv:
                    argv.append(tok)
        hb = os.path.join(self.run_dir, f"worker_{i}.hb")
        try:  # stall ages are measured against THIS attempt only
            os.unlink(hb)
        except OSError:
            pass
        env = {
            **self.base_env,
            "PIO_NUM_PROCESSES": str(cfg.num_workers),
            "PIO_PROCESS_ID": str(i),
            ENV_GANG_WORKER: "1",
            ENV_HEARTBEAT_FILE: hb,
            "PIO_WORKER_HEARTBEAT_MS": str(cfg.heartbeat_ms),
            **self._env_for(attempt, i),
        }
        if self.wire_coordinator and port is not None:
            env["PIO_COORDINATOR_ADDRESS"] = \
                f"{self.coordinator_host}:{port}"
        if self.gang_instance_id:
            env[ENV_GANG_INSTANCE_ID] = self.gang_instance_id
        log_path = os.path.join(self.run_dir, f"worker_{i}.log")
        logf = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                argv, env=env, stdout=logf, stderr=subprocess.STDOUT)
        finally:
            logf.close()  # the child holds its own fd now
        return _Worker(i, proc, hb, log_path, time.monotonic())

    def _spawn_gang(self, resume: bool) -> None:
        cfg = self.config
        port = self._free_port() if self.wire_coordinator else None
        self._workers = [
            self._spawn_worker(i, port, resume, self._attempt)
            for i in range(cfg.num_workers)
        ]
        self._event("gangStart", attempt=self._attempt, resume=resume,
                    port=port,
                    pids=[w.proc.pid for w in self._workers])
        log.info("gang attempt %d: %d worker(s) up (resume=%s, "
                 "coordinator port %s)", self._attempt, cfg.num_workers,
                 resume, port)

    def _kill_gang(self, sig: int = signal.SIGKILL) -> None:
        for w in self._workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(sig)
                except OSError:
                    pass
        for w in self._workers:
            try:
                w.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover
                w.proc.kill()
                w.proc.wait()

    def _check_failure(self) -> Optional[dict]:
        """One monitor sweep → failure descriptor or None."""
        cfg = self.config
        now = time.monotonic()
        for w in self._workers:
            rc = w.proc.poll()
            if rc is not None:
                if rc not in (0, DRAIN_EXIT_CODE):
                    return {"reason": "exit", "worker": w.idx, "rc": rc}
                continue
            age = w.heartbeat_age_ms()
            if age is None:
                if (now - w.spawned_at) * 1000.0 > cfg.init_grace_ms:
                    return {"reason": "no_heartbeat", "worker": w.idx}
            elif age > cfg.stall_ms:
                return {"reason": "stall", "worker": w.idx,
                        "age_ms": round(age, 1)}
        # Workers exiting 0 before their peers is normal (they don't all
        # reach exit in the same poll window); a survivor blocked in a
        # dead collective is caught by the stall detector above.
        return None

    def _publish(self, state_code: float) -> None:
        _, alive_g, age_g, state_g = _metrics()
        workers = []
        for w in self._workers:
            alive = w.proc.poll() is None
            age = w.heartbeat_age_ms()
            alive_g.labels(str(w.idx)).set(1.0 if alive else 0.0)
            age_g.labels(str(w.idx)).set(-1.0 if age is None else age / 1000.0)
            workers.append({
                "worker": w.idx,
                "pid": w.proc.pid,
                "alive": alive,
                "returncode": w.proc.poll(),
                "heartbeatAgeMs": age,
                "retiring": w.idx in self._retiring,
                "restarts": (self.worker_restarts[w.idx]
                             if w.idx < len(self.worker_restarts) else 0),
                "log": w.log_path,
            })
        state_g.set(state_code)
        doc = {
            "gangInstanceId": self.gang_instance_id,
            "state": self.state,
            "attempt": self._attempt,
            "restarts": self.restarts,
            "config": self.config.to_json(),
            "workers": workers,
            "events": self.events,
        }
        tmp = os.path.join(self.run_dir, ".supervisor.json.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, os.path.join(self.run_dir, "supervisor.json"))
        except OSError:  # pragma: no cover - run_dir ripped out under us
            log.debug("could not publish supervisor status", exc_info=True)

    def _drain(self) -> None:
        """SIGTERM every worker, give them the drain budget to
        checkpoint and exit, SIGKILL stragglers."""
        self.state = "draining"
        self._event("drainStart")
        self._publish(2.0)
        for w in self._workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.config.drain_ms / 1000.0
        while time.monotonic() < deadline:
            if all(w.proc.poll() is not None for w in self._workers):
                break
            time.sleep(self.config.poll_ms / 1000.0)
        stragglers = [w.idx for w in self._workers if w.proc.poll() is None]
        self._kill_gang()
        self._event("drainDone", stragglers=stragglers)
        if stragglers:
            log.warning("drain deadline hit; SIGKILLed worker(s) %s — the "
                        "run resumes from the last completed checkpoint",
                        stragglers)

    def _tail(self, w: _Worker, n: int = 2000) -> str:
        try:
            with open(w.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - n))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    def _check_service_failure(self) -> Optional[dict]:
        """Per-worker failure sweep for ``restart_scope='worker'``: ANY
        exit is a failure (a supervised service worker has no reason to
        stop), plus the same no-first-beat / heartbeat-stall detection
        the gang path uses."""
        cfg = self.config
        now = time.monotonic()
        for w in self._workers:
            if w.idx in self._retiring:
                continue  # an exit is the POINT of retirement
            rc = w.proc.poll()
            if rc is not None:
                return {"reason": "exit", "worker": w.idx, "rc": rc}
            age = w.heartbeat_age_ms()
            if age is None:
                if (now - w.spawned_at) * 1000.0 > cfg.init_grace_ms:
                    return {"reason": "no_heartbeat", "worker": w.idx}
            elif age > cfg.stall_ms:
                return {"reason": "stall", "worker": w.idx,
                        "age_ms": round(age, 1)}
        return None

    def _run_service(self) -> str:
        """Per-worker supervision: a failed worker is killed and
        relaunched alone (no checkpoint, no resume argv — a fresh
        server whose startup replays its own WAL partition) while its
        peers keep serving. Terminal states: ``drained`` (stop
        requested) or ``failed`` (one worker exhausted its per-worker
        restart budget)."""
        from ..common.resilience import RetryPolicy

        cfg = self.config
        backoff = RetryPolicy(max_attempts=cfg.max_restarts + 1,
                              base_delay=0.5, max_delay=15.0)
        per_worker_restarts = self.worker_restarts
        self._attempt = 0
        self.state = "running"
        self._spawn_gang(resume=False)
        self._publish(1.0)
        last_publish = 0.0
        while True:
            if self._stop.is_set():
                self._drain()
                self.state = DRAINED
                self._publish(0.0)
                log.info("service drained cleanly (%d worker(s))",
                         len(self._workers))
                return DRAINED
            self._apply_membership()
            self._reap_retiring()
            failure = self._check_service_failure()
            if failure is not None:
                idx = failure["worker"]
                bad = self._worker_by_idx(idx)
                log.warning("service worker %d failed (%s); relaunching "
                            "it. log tail:\n%s", idx, failure,
                            self._tail(bad))
                self._event("workerFailure", **failure)
                if bad.proc.poll() is None:
                    try:
                        bad.proc.send_signal(signal.SIGKILL)
                    except OSError:
                        pass
                    bad.proc.wait()
                restarts_c, *_ = _metrics()
                restarts_c.labels(failure["reason"]).inc()
                while len(per_worker_restarts) <= idx:
                    per_worker_restarts.append(0)
                per_worker_restarts[idx] += 1
                self.restarts += 1
                if per_worker_restarts[idx] > cfg.max_restarts:
                    self.state = FAILED
                    self._event("gaveUp", worker=idx,
                                restarts=per_worker_restarts[idx])
                    self._publish(3.0)
                    self._kill_gang()
                    log.error("worker %d exhausted its restart budget "
                              "(%d); stopping the service", idx,
                              cfg.max_restarts)
                    return FAILED
                delay = backoff.backoff(per_worker_restarts[idx] - 1)
                self._event("workerRestart", worker=idx,
                            n=per_worker_restarts[idx],
                            backoff_s=round(delay, 3))
                # bounded wait that still honours a stop request — a
                # drain must not be stuck behind a restart backoff,
                # and a stop that lands DURING the backoff must not
                # spawn (and immediately kill) a fresh worker
                if self._stop.wait(delay):
                    continue
                self._attempt = per_worker_restarts[idx]
                self._workers[self._workers.index(bad)] = \
                    self._spawn_worker(idx, None, resume=False,
                                       attempt=per_worker_restarts[idx])
                self._publish(1.0)
            now = time.monotonic()
            if now - last_publish >= 1.0:
                self._publish(1.0)
                last_publish = now
            time.sleep(cfg.poll_ms / 1000.0)

    def run(self) -> str:
        """Supervise to a terminal state: ``completed`` (every worker
        exited 0), ``drained`` (stop requested; checkpoint preserved),
        or ``failed`` (restart budget exhausted)."""
        if self.restart_scope == "worker":
            return self._run_service()
        cfg = self.config
        restart_backoff = None
        resume = False
        while True:
            if self._stop.is_set():  # SIGTERM landed during backoff
                self.state = DRAINED
                self._publish(0.0)
                return DRAINED
            self._attempt = self.restarts
            self.state = "running"
            self._spawn_gang(resume=resume)
            self._publish(1.0)
            last_publish = 0.0
            failure = None
            while True:
                if self._stop.is_set():
                    self._drain()
                    self.state = DRAINED
                    self._publish(0.0)
                    log.info("gang drained cleanly; resume with "
                             "`pio train --resume` (checkpoints kept)")
                    return DRAINED
                rcs = [w.proc.poll() for w in self._workers]
                if all(rc in (0, DRAIN_EXIT_CODE) for rc in rcs):
                    if any(rc == DRAIN_EXIT_CODE for rc in rcs):
                        # Workers drained without our stop flag: someone
                        # SIGTERMed them directly. Honor it — don't
                        # relaunch a run the operator just stopped.
                        self.state = DRAINED
                        self._event("drainedByWorkers", rcs=rcs)
                        self._publish(0.0)
                        log.info("workers drained on their own SIGTERM; "
                                 "checkpoints kept, resume with --resume")
                        return DRAINED
                    self.state = COMPLETED
                    self._event("completed")
                    self._publish(0.0)
                    return COMPLETED
                failure = self._check_failure()
                if failure is not None:
                    break
                now = time.monotonic()
                if now - last_publish >= 1.0:
                    self._publish(1.0)
                    last_publish = now
                time.sleep(cfg.poll_ms / 1000.0)

            self._event("failure", **failure)
            bad = self._workers[failure["worker"]]
            log.warning(
                "worker %d failed (%s); killing the gang. log tail:\n%s",
                failure["worker"], failure, self._tail(bad))
            self._kill_gang()
            self._event("gangKilled")
            restarts_c, *_ = _metrics()
            restarts_c.labels(failure["reason"]).inc()
            if self.restarts >= cfg.max_restarts:
                self.state = FAILED
                self._event("gaveUp", restarts=self.restarts)
                self._publish(3.0)
                log.error("restart budget exhausted (%d); giving up — the "
                          "last checkpoint remains resumable",
                          self.restarts)
                return FAILED
            self.restarts += 1
            resume = True
            if restart_backoff is None:
                from ..common.resilience import RetryPolicy

                restart_backoff = RetryPolicy(
                    max_attempts=cfg.max_restarts + 1, base_delay=0.5,
                    max_delay=15.0)
            delay = restart_backoff.backoff(self.restarts - 1)
            self._event("restart", n=self.restarts,
                        backoff_s=round(delay, 3))
            log.info("gang restart %d/%d in %.2fs (resume from latest "
                     "checkpoint)", self.restarts, cfg.max_restarts, delay)
            time.sleep(delay)
