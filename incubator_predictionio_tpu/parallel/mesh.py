"""Device-mesh construction + sharding helpers.

The reference scales via Spark executors + shuffle (external dependency;
SURVEY.md §2.10). The TPU-native equivalent: a `jax.sharding.Mesh` over all
devices with named axes, NamedSharding annotations on arrays, and XLA
emitting collectives over ICI from pjit/shard_map. Every algorithm in
models/ trains against a mesh obtained here.

Axis conventions:
- ``DATA_AXIS`` ('d'): batch/entity-row sharding — users in the ALS user
  solve, examples in NB/LR sufficient-stat reductions (psum over 'd').
- ``MODEL_AXIS`` ('m'): reserved for factor/feature sharding when a factor
  matrix exceeds one chip's HBM (ALX-style; 2-D meshes are constructed on
  demand via mesh_from_devices(shape=(dp, mp))).
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "d"
MODEL_AXIS = "m"


def local_device_count() -> int:
    return jax.local_device_count()


def mesh_from_devices(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a mesh over the given devices (default: all).

    shape=None → 1-D mesh over every device on axis 'd'.
    shape=(dp, mp) with axis_names=('d','m') → 2-D factor-sharded layouts.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    arr = np.array(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names[: arr.ndim]))


_default_mesh: Optional[Mesh] = None


def _mesh_shape_from_env() -> Optional[tuple[int, ...]]:
    """PIO_MESH_SHAPE: "8" → 1-D data mesh over 8 devices; "4x2" →
    2-D (d, m)=(4, 2) ALX mesh. Set directly or via the CLI passthrough
    tier (`pio train -- --mesh=4x2`, SURVEY.md §5.6c)."""
    from ..common import envknobs

    spec = envknobs.env_str("PIO_MESH_SHAPE", "")
    if not spec:
        return None
    try:
        dims = tuple(int(s) for s in spec.lower().split("x"))
    except ValueError:
        raise ValueError(f"PIO_MESH_SHAPE={spec!r}: expected D or DxM")
    if len(dims) > 2 or any(d < 1 for d in dims):
        raise ValueError(f"PIO_MESH_SHAPE={spec!r}: expected D or DxM")
    return dims


def default_mesh(refresh: bool = False) -> Mesh:
    """Process-wide default mesh (cached): 1-D over all devices unless
    PIO_MESH_SHAPE overrides the shape."""
    global _default_mesh
    if _default_mesh is None or refresh:
        shape = _mesh_shape_from_env()
        if shape is None:
            _default_mesh = mesh_from_devices()
        else:
            n = int(np.prod(shape))
            devices = jax.devices()
            if n > len(devices):
                raise ValueError(
                    f"PIO_MESH_SHAPE/--mesh requests {shape} = {n} devices "
                    f"but only {len(devices)} are available")
            chosen = devices[:n]
            if jax.process_count() > 1:
                # every process must own a shard or its collectives hang
                # with an opaque sharding error
                procs = {d.process_index for d in chosen}
                if len(procs) != jax.process_count():
                    raise ValueError(
                        f"PIO_MESH_SHAPE/--mesh shape {shape} uses only "
                        f"devices of processes {sorted(procs)} but "
                        f"{jax.process_count()} processes are running — "
                        "the mesh must span every process")
            axes = (DATA_AXIS, MODEL_AXIS)[: len(shape)]
            _default_mesh = mesh_from_devices(
                shape=shape, axis_names=axes, devices=chosen)
    return _default_mesh


def shard_rows(mesh: Mesh, ndim: int = 1, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits dim 0 over the data axis, replicating the rest."""
    spec = P(axis, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def device_put_sharded_rows(x, mesh: Mesh, axis: str = DATA_AXIS):
    """Host numpy → row-sharded device array. Row count must divide the
    axis size (callers pad with pad_rows first)."""
    x = np.asarray(x)
    return fast_put(x, shard_rows(mesh, x.ndim, axis))


def fast_put(arr, sharding):
    """``jax.device_put`` with the single-device fast path.

    A NamedSharding put on a ONE-device mesh routes through PJRT's
    sharded-copy machinery; through the sandbox's remote-PJRT tunnel
    that path measured ~30x slower than the plain single-device put
    (0.65 s vs 22 ms for the same 32 MB — see BASELINE.md decomposition
    notes). A single-device NamedSharding is equivalent
    (`is_equivalent_to`) to plain placement on that device, so jit
    reuses the buffer without any resharding copy."""
    devices = getattr(sharding, "device_set", None)
    if devices is not None and len(devices) == 1:
        return jax.device_put(arr, next(iter(devices)))
    return jax.device_put(arr, sharding)


def pad_rows(x: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    """Pad dim 0 up to a multiple (static shapes for XLA; masked later)."""
    n = x.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return x
    pad_width = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_width, constant_values=fill)


@contextlib.contextmanager
def with_mesh(mesh: Mesh):
    with mesh:
        yield mesh
