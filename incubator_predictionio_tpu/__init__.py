"""incubator_predictionio_tpu — a TPU-native machine-learning server.

A ground-up rebuild of the capabilities of Apache PredictionIO
(reference: fqc/incubator-predictionio — see /root/repo/SURVEY.md) on
JAX/XLA instead of Scala/Spark:

- DASE engine architecture (DataSource, Preparator, Algorithm, Serving
  + Evaluation) as Python classes producing jax arrays/pytrees
  (reference: core/src/main/scala/org/apache/predictionio/controller/).
- Event Server with the PredictionIO REST ingestion API
  (reference: data/src/main/scala/org/apache/predictionio/data/api/).
- Pluggable storage registry driven by PIO_STORAGE_* env vars
  (reference: data/.../data/storage/Storage.scala).
- Training workflow that runs DASE pipelines as pjit'd XLA programs on
  a TPU mesh (reference: core/.../workflow/CreateWorkflow.scala) —
  no Spark executors; collectives over ICI replace shuffles.
- Deployment server exposing trained models behind POST /queries.json
  (reference: core/.../workflow/CreateServer.scala).
- CLI `pio` with the familiar verb set
  (reference: tools/.../tools/console/Console.scala).

Subpackage map (SURVEY.md layer map in parentheses):
  data/       storage + event model + event server + event stores (L1-L3)
  controller/ DASE controller API (L4)
  workflow/   train/eval/deploy runtime (L5)
  tools/      CLI, admin, dashboard, export/import (L6)
  e2/         ML helper lib (L7)
  models/     bundled template algorithm families (L8 analog)
  ops/        JAX/XLA numeric kernels (ALS solves, segment ops, top-k, LLR)
  parallel/   mesh/sharding/collective helpers, multi-host init
  utils/      config, logging, json
"""

__version__ = "0.1.0"
