"""Pallas TPU kernels for the ALS hot loop.

Profiling the ml20m half-step on a v5e chip (see bench.py) shows XLA's
batched ``cholesky`` + ``cho_solve`` of the [n_rows, k, k] normal equations
dominating the iteration (~575 ms for 138k rank-32 systems — the solver
lowering is latency-bound on small matrices). The MXU/VPU-friendly
replacement here solves all systems with one VMEM-resident Gauss-Jordan
sweep:

- The batch lives on the *lane* dimension: matrices are transposed to
  [k, k, N] so every elimination step is a [k, C]-shaped vector op across
  C systems at full lane width (C a multiple of 128).
- Each grid step copies a C-wide slab into VMEM scratch and runs the
  k-step elimination entirely on-chip — HBM traffic is exactly one read
  of A/b and one write of x (the XLA formulation re-streams the whole
  [N, k, k] array every elimination step).
- No pivoting: every system is SPD by construction (normal equations
  plus a λ·I ridge — ops/als.py adds 1e-6 even for empty rows).

The reference has no analog: its solves happen inside MLlib's
``CholeskyDecomposition.solve`` on the Spark executors (SURVEY.md §2.9).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _gj_eliminate(a_s, b_s, *, k: int):
    """Run the elimination on VMEM scratch [k, k, C] / [k, C]; return x.

    Normalization-free Gauss-Jordan: pivot rows are never scaled in place
    (row j's elimination factor is masked to zero, so row j survives
    verbatim); after k elimination steps A is diagonal and one division
    by the diagonal recovers x. This halves the VPU traffic of the naive
    formulation, whose per-step masked full-block `where` store of the
    normalized pivot row cost as much as the elimination FMA itself.
    """
    from jax.experimental import pallas as pl

    row_ids = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)  # [k, 1]

    def step(j, _):
        # Dynamic slicing happens on the refs (Mosaic lowers pl.ds ref
        # indexing; dynamic_slice on values is not implemented).
        rowj = a_s[pl.ds(j, 1), :, :][0]                    # [k, C] (raw)
        piv = a_s[pl.ds(j, 1), pl.ds(j, 1), :][0]           # [1, C] a[j,j]
        inv = 1.0 / piv                                     # [1, C]
        bj = b_s[pl.ds(j, 1), :]                            # [1, C] (raw)

        f = a_s[:, pl.ds(j, 1), :][:, 0, :] * inv           # [k, C] col j
        # Row j eliminates every row but itself (it is finished as-is).
        f = jnp.where(row_ids == j, 0.0, f)

        a_s[...] = a_s[...] - f[:, None, :] * rowj[None, :, :]
        b_s[...] = b_s[...] - f * bj
        return 0

    jax.lax.fori_loop(0, k, step, 0)
    # A is now diagonal; extract it with an iota mask (no dynamic loads;
    # i1 vectors cannot grow a minor dim under Mosaic, so mask in f32).
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (k, k), 1)
    eye_mask = (row_ids == col_ids).astype(jnp.float32)     # [k, k]
    diag = jnp.sum(a_s[...] * eye_mask[:, :, None], axis=1)  # [k, C]
    return b_s[...] / diag


def _gauss_jordan_kernel(a_ref, b_ref, x_ref, a_s, b_s, *, k: int):
    """Solve C systems: a_ref [k, k, C], b_ref [k, C] → x_ref [k, C].

    a_s/b_s are VMEM scratch copies mutated in place by the elimination.
    """
    a_s[...] = a_ref[...]
    b_s[...] = b_ref[...]
    x_ref[...] = _gj_eliminate(a_s, b_s, k=k)


def _gauss_jordan_kernel_wide(a_hbm, b_hbm, x_hbm, a_s, b_s, sems, *, k: int):
    """Wide-rank slab (96 < k ≤ 128): a_hbm [G, k, k, C], C = 128.

    At k=128 the f32 [k, k, C] slab is 8 MB, so the pipelined kernel's
    double-buffered input block plus scratch copy (24 MB) exceeds VMEM
    (and Mosaic rejects lane blocks narrower than 128). Slabs therefore
    stay in HBM (ANY space) and each grid step DMAs ONE slab into a
    single VMEM scratch — no double buffering. The elimination is
    compute-bound (k⁴·C/k ≈ 0.5 GFLOP/slab against 8 MB of traffic), so
    the lost DMA/compute overlap is noise.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    cp_a = pltpu.make_async_copy(a_hbm.at[i], a_s, sems.at[0])
    cp_b = pltpu.make_async_copy(b_hbm.at[i], b_s, sems.at[1])
    cp_a.start()
    cp_b.start()
    cp_a.wait()
    cp_b.wait()
    b_s[...] = _gj_eliminate(a_s, b_s, k=k)
    cp_x = pltpu.make_async_copy(b_s, x_hbm.at[i], sems.at[2])
    cp_x.start()
    cp_x.wait()


@functools.partial(jax.jit, static_argnames=("interpret", "vma"))
def _solve_lanes(a_t, b_t, *, interpret: bool = False, vma=None):
    """a_t [k, k, Np], b_t [k, Np] (Np multiple of 128) → x_t [k, Np].

    ``vma``: when called inside ``shard_map`` (check_vma=True), the mesh
    axes the output varies over — forwarded to the out_shape aval.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, _, n = a_t.shape
    if vma is not None:
        out_shape = jax.ShapeDtypeStruct((k, n), jnp.float32, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct((k, n), jnp.float32)
    # Slab width: full lane utilization, capped so the f32 [k, k, C]
    # input block (double-buffered by the pipeline) plus its scratch copy
    # stays under the ~16 MB VMEM budget. Ranks past 96 take the wide
    # path (_solve_slabs_wide) instead.
    c = 512 if k <= 32 else (256 if k <= 48 else 128)
    c = min(c, n)
    grid = (n // c,)

    kernel = functools.partial(_gauss_jordan_kernel, k=k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((k, k, c), lambda i: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, c), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, c), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((k, k, c), jnp.float32),
            pltpu.VMEM((k, c), jnp.float32),
        ],
        interpret=interpret,
    )(a_t, b_t)


@functools.partial(jax.jit, static_argnames=("interpret", "vma"))
def _solve_slabs_wide(a_g, b_g, *, interpret: bool = False, vma=None):
    """a_g [G, k, k, 128], b_g [G, k, 128] → x_g [G, k, 128] (96 < k ≤ 128).

    Slab-major layout: the caller pre-transposes so each grid step's slab
    is one contiguous [k, k, 128] block — the kernel's manual DMA is a
    single contiguous transfer (see _gauss_jordan_kernel_wide).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    g, k, _, c = a_g.shape
    if vma is not None:
        out_shape = jax.ShapeDtypeStruct((g, k, c), jnp.float32, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct((g, k, c), jnp.float32)
    kernel = functools.partial(_gauss_jordan_kernel_wide, k=k)
    return pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((k, k, c), jnp.float32),
            pltpu.VMEM((k, c), jnp.float32),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(a_g, b_g)


def _solve_reference(a, b):
    """XLA fallback: batched Cholesky solve (CPU and rank > 128)."""
    chol = jnp.linalg.cholesky(a)
    return jax.scipy.linalg.cho_solve((chol, True), b[..., None])[..., 0]


def batched_spd_solve(a, b, *, use_pallas: bool | None = None,
                      platform: str | None = None,
                      interpret: bool = False, vma=None):
    """Solve N independent SPD systems a[i] @ x[i] = b[i].

    a: [N, k, k] float32, b: [N, k] float32 → x [N, k] float32.

    ``use_pallas=None`` auto-selects: the Pallas kernel when ``platform``
    is "tpu" and k ≤ 128 (the kernel's VMEM slab cap), the XLA Cholesky
    path otherwise. ``platform`` must be the platform of the devices that
    will EXECUTE this computation — pass the mesh's device platform when
    calling under shard_map/jit-with-shardings; it defaults to
    ``jax.default_backend()``, which is only correct outside any explicit
    mesh (the driver dry-runs CPU meshes while a TPU stays the process
    default backend). Traceable (jit/shard_map safe): all shape logic is
    static.
    """
    n, k = b.shape
    if use_pallas is None:
        if platform is None:
            platform = jax.default_backend()
        use_pallas = platform == "tpu" and k <= 128
    if not use_pallas:
        return _solve_reference(a, b)

    kp = _round_up(k, 8)
    # Lanes path: multiple of 512 so every slab width (512/256/128)
    # divides the batch. Wide path: its slab width is always 128, and a
    # padding slab is ~0.5 GFLOP of pure identity solves — don't round
    # further than needed.
    npad = _round_up(max(n, 1), 128 if kp > 96 else 512)
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    if kp != k:
        # Pad with identity diagonal: padded coords solve to x=0 and do
        # not couple to the real ones.
        eye_pad = jnp.eye(kp, dtype=jnp.float32)[k:]  # [kp-k, kp]
        a = jnp.pad(a, ((0, 0), (0, kp - k), (0, kp - k)))
        a = a.at[:, k:, :].set(eye_pad[None])
        b = jnp.pad(b, ((0, 0), (0, kp - k)))
    if npad != n:
        pad = jnp.eye(kp, dtype=jnp.float32)[None].repeat(npad - n, axis=0)
        a = jnp.concatenate([a, pad], axis=0)
        b = jnp.concatenate([b, jnp.zeros((npad - n, kp), jnp.float32)], axis=0)

    vma_f = None if vma is None else frozenset(vma)
    if kp > 96:
        # Wide-rank path: slab-major [G, kp, kp, 128] so each slab is one
        # contiguous manual-DMA transfer inside the kernel.
        c = 128
        g = npad // c
        a_g = jnp.transpose(a.reshape(g, c, kp, kp), (0, 2, 3, 1))
        b_g = jnp.transpose(b.reshape(g, c, kp), (0, 2, 1))
        x_g = _solve_slabs_wide(a_g, b_g, interpret=interpret, vma=vma_f)
        return jnp.transpose(x_g, (0, 2, 1)).reshape(npad, kp)[:n, :k]

    a_t = jnp.transpose(a, (1, 2, 0))  # [kp, kp, Np] — batch on lanes
    b_t = jnp.transpose(b, (1, 0))     # [kp, Np]
    x_t = _solve_lanes(a_t, b_t, interpret=interpret, vma=vma_f)
    return jnp.transpose(x_t, (1, 0))[:n, :k]
