"""Ranking-quality metric kernels for the shadow scorer (MAP@k /
NDCG@k / AUC) plus the windowed canary-vs-last-good verdict.

Reference behaviour: MLlib's RankingMetrics / BinaryClassificationMetrics
evaluator suite (arxiv 1505.06807) — the offline evaluator catalog —
re-cut for ONLINE use inside the serving loop, where per-sample overhead
must stay cheap at ALX-style serving scale points (arxiv 2112.02194):
one jitted kernel over a padded [batch, k] relevance matrix, one host
transfer, shapes bucketed so a steady sample stream reuses a single
executable.

Conventions (shared by every caller — the deltas only mean something if
both windows are scored identically):

- A *sample* is one ranked item list (best first, truncated to k) plus
  the set of held-out relevant items (the user's next events).
- Samples with an empty label set are invalid (nothing to grade).
- AP@k divides by min(|labels|, k): a perfect top-k scores 1.0 even
  when more than k items are relevant.
- NDCG@k uses binary gains with 1/log2(pos+1) discounts; IDCG places
  the min(|labels|, k) relevant items first.
- AUC is in-list: the probability a relevant item outranks an
  irrelevant one *within the returned list*; samples whose list is all
  relevant or all irrelevant carry no pairs and are excluded from the
  AUC mean (tracked separately as ``n_auc``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .topk import pad_batch_pow2

__all__ = ["MetricWindow", "bucket_k_eval", "quality_verdict",
           "ranking_metrics"]


@jax.jit
def _ranking_metrics(rel, pmask, n_rel, valid):
    # rel:   [b, k] 0/1 relevance at each ranked position
    # pmask: [b, k] 1 where a real ranked item exists (lists may be
    #        shorter than k; real items are a prefix)
    # n_rel: [b] held-out relevant-item count per sample
    # valid: [b] 1 for real samples (batch rows are pow2-padded)
    k = rel.shape[1]
    pos = jnp.arange(1, k + 1, dtype=jnp.float32)
    rel = rel * pmask
    cum = jnp.cumsum(rel, axis=1)
    # AP@k: precision is only read at relevant positions, all inside the
    # real prefix, so the padded tail never contributes
    ap = (rel * (cum / pos[None, :])).sum(axis=1)
    ap = ap / jnp.maximum(jnp.minimum(n_rel, float(k)), 1.0)
    disc = 1.0 / jnp.log2(pos + 1.0)
    dcg = (rel * disc[None, :]).sum(axis=1)
    ideal = (pos[None, :] <= jnp.minimum(n_rel, float(k))[:, None])
    idcg = (ideal.astype(jnp.float32) * disc[None, :]).sum(axis=1)
    ndcg = dcg / jnp.maximum(idcg, 1e-9)
    # in-list AUC via one cumsum: for each relevant position, the
    # concordant pairs are the negatives ranked BELOW it
    neg = pmask * (1.0 - rel)
    neg_above = jnp.cumsum(neg, axis=1) - neg
    n_pos = rel.sum(axis=1)
    n_neg = neg.sum(axis=1)
    concordant = (rel * (n_neg[:, None] - neg_above)).sum(axis=1)
    pairs = n_pos * n_neg
    auc = concordant / jnp.maximum(pairs, 1.0)
    has_pairs = valid * (pairs > 0).astype(jnp.float32)
    n = valid.sum()
    n_auc = has_pairs.sum()
    return (
        (ap * valid).sum() / jnp.maximum(n, 1.0),
        (ndcg * valid).sum() / jnp.maximum(n, 1.0),
        (auc * has_pairs).sum() / jnp.maximum(n_auc, 1.0),
        n,
        n_auc,
    )


def bucket_k_eval(k: int) -> int:
    """Pow2 (≥8) k bucket so callers varying k share executables —
    ops/topk.py's bucket_k without the catalog cap (labels are not
    bounded by a catalog here)."""
    return max(8, 1 << max(int(k) - 1, 0).bit_length())


def ranking_metrics(ranked, labels, k: int) -> dict:
    """Score a batch of samples: ``ranked`` is a sequence of ranked
    item-id lists (best first), ``labels`` the parallel sequence of
    held-out relevant-item collections. Returns mean ``map``/``ndcg``/
    ``auc`` plus the sample counts they were averaged over (``n``
    graded samples, ``n_auc`` of them carrying AUC pairs)."""
    b = len(ranked)
    zero = {"map": 0.0, "ndcg": 0.0, "auc": 0.0, "n": 0, "n_auc": 0}
    if b == 0:
        return zero
    k = max(1, int(k))
    kp = bucket_k_eval(k)
    rel = np.zeros((b, kp), np.float32)
    pmask = np.zeros((b, kp), np.float32)
    n_rel = np.zeros((b,), np.float32)
    valid = np.zeros((b,), np.float32)
    for i, (items, labs) in enumerate(zip(ranked, labels)):
        labs = set(labs)
        if not labs:
            continue
        valid[i] = 1.0
        n_rel[i] = float(len(labs))
        for j, item in enumerate(items[:k]):
            pmask[i, j] = 1.0
            if item in labs:
                rel[i, j] = 1.0
    if not valid.any():
        return zero
    out = _ranking_metrics(
        jnp.asarray(pad_batch_pow2(rel)),
        jnp.asarray(pad_batch_pow2(pmask)),
        jnp.asarray(pad_batch_pow2(n_rel)),
        jnp.asarray(pad_batch_pow2(valid)),
    )
    # single host transfer (ops/topk.py idiom): each device_get is a
    # round-trip through a remote-PJRT tunnel
    m, nd, auc, n, n_auc = jax.device_get(out)
    return {"map": float(m), "ndcg": float(nd), "auc": float(auc),
            "n": int(round(float(n))), "n_auc": int(round(float(n_auc)))}


class MetricWindow:
    """Host-side accumulator for one watch window: fold per-tick
    ``ranking_metrics`` batches into running sums so the verdict reads
    a whole-window mean, not the last tick's."""

    __slots__ = ("map_sum", "ndcg_sum", "auc_sum", "n", "n_auc")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.map_sum = 0.0
        self.ndcg_sum = 0.0
        self.auc_sum = 0.0
        self.n = 0
        self.n_auc = 0

    def add(self, metrics: dict) -> None:
        n = int(metrics.get("n", 0))
        if n <= 0:
            return
        self.map_sum += metrics["map"] * n
        self.ndcg_sum += metrics["ndcg"] * n
        self.n += n
        n_auc = int(metrics.get("n_auc", 0))
        self.auc_sum += metrics.get("auc", 0.0) * n_auc
        self.n_auc += n_auc

    def means(self) -> dict:
        n = max(self.n, 1)
        return {"map": self.map_sum / n, "ndcg": self.ndcg_sum / n,
                "auc": self.auc_sum / max(self.n_auc, 1),
                "n": self.n, "n_auc": self.n_auc}


def quality_verdict(canary: dict, last_good: dict, *,
                    min_samples: int, max_drop: float):
    """Windowed canary-vs-last-good comparison with a minimum-sample
    gate. Both inputs are ``MetricWindow.means()``-shaped dicts scored
    over the SAME queries and labels. Returns ``(breach, deltas)``:
    ``deltas[metric] = last_good − canary`` (positive = the canary is
    worse), and ``breach`` is True only when BOTH windows carry at
    least ``min_samples`` graded samples AND the NDCG drop exceeds
    ``max_drop`` — NDCG@k is the trigger metric (rank-sensitive and
    bounded); MAP/AUC ride along for telemetry. The sample gate is why
    thin traffic can't false-trigger: an unlucky 3-query window is not
    evidence."""
    deltas = {m: round(float(last_good.get(m, 0.0))
                       - float(canary.get(m, 0.0)), 6)
              for m in ("map", "ndcg", "auc")}
    floor = max(1, int(min_samples))
    n = min(int(canary.get("n", 0)), int(last_good.get("n", 0)))
    breach = n >= floor and deltas["ndcg"] > float(max_drop)
    return breach, deltas
