"""Blocked-COO layout: ragged event data → dense MXU-friendly tiles.

The reference shrugs ragged per-user histories into Spark `groupByKey`;
XLA wants static shapes. This module lays a COO triple (row, col, value)
out as fixed-width blocks: each row's entries are split into chunks of
``block_len``; every chunk becomes one dense tile row with a 0/1 mask.
Per-row reductions are then two steps, both batched and static:
  1. a [B, L, k] batched matmul / einsum over tiles (MXU), and
  2. a segment-sum of tile results onto rows.
This is the layout trick from ALX (PAPERS.md: arxiv 2112.02194) adapted to
one uniform block width + mask instead of multiple size buckets.

Everything here is host-side numpy (fully vectorized — no Python loop over
the nnz) and runs once per training job.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class BlockedRows:
    """Dense tiling of a sparse [n_rows, *] matrix's nonzeros.

    col  [B, L] int32 — column index of each entry (0 where padded)
    val  [B, L] float32 — entry value (0 where padded)
    mask [B, L] float32 — 1 for real entries
    block_row [B] int32 — which (global) row each tile belongs to
    n_rows — logical row count
    counts [n_rows] int32 — nnz per row
    """

    col: np.ndarray
    val: np.ndarray
    mask: np.ndarray
    block_row: np.ndarray
    n_rows: int
    counts: np.ndarray
    # Column index stored in padding slots. 0 by default; ops/als.py points
    # it at a sentinel zero-factor row so the device loop needs no mask.
    pad_col: int = 0

    @property
    def n_blocks(self) -> int:
        return self.col.shape[0]

    @property
    def block_len(self) -> int:
        return self.col.shape[1]


def build_blocked(
    row: np.ndarray,
    col: np.ndarray,
    val: np.ndarray,
    n_rows: int,
    block_len: int = 32,
    pad_col: int = 0,
) -> BlockedRows:
    """Tile a COO triple by row. O(nnz log nnz) host time, vectorized."""
    row = np.asarray(row, dtype=np.int64)
    col = np.asarray(col, dtype=np.int32)
    val = np.asarray(val, dtype=np.float32)
    nnz = row.shape[0]
    L = int(block_len)

    order = np.argsort(row, kind="stable")
    row_s, col_s, val_s = row[order], col[order], val[order]

    counts = np.bincount(row_s, minlength=n_rows).astype(np.int64)
    blocks_per_row = (counts + L - 1) // L
    n_blocks = max(int(blocks_per_row.sum()), 1)

    # Position of each sorted entry within its row.
    row_start = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=row_start[1:])
    pos_in_row = np.arange(nnz, dtype=np.int64) - row_start[row_s]

    # Global block id of each entry, and its slot within the block.
    block_offset = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(blocks_per_row, out=block_offset[1:])
    entry_block = block_offset[row_s] + pos_in_row // L
    entry_slot = pos_in_row % L

    col_b = np.full((n_blocks, L), pad_col, dtype=np.int32)
    val_b = np.zeros((n_blocks, L), dtype=np.float32)
    mask_b = np.zeros((n_blocks, L), dtype=np.float32)
    flat = entry_block * L + entry_slot
    col_b.reshape(-1)[flat] = col_s
    val_b.reshape(-1)[flat] = val_s
    mask_b.reshape(-1)[flat] = 1.0

    block_row = np.repeat(
        np.arange(n_rows, dtype=np.int64), blocks_per_row
    ).astype(np.int32)
    if block_row.shape[0] == 0:  # all-empty matrix: single padded block
        block_row = np.zeros(1, dtype=np.int32)

    return BlockedRows(
        col=col_b, val=val_b, mask=mask_b, block_row=block_row,
        n_rows=n_rows, counts=counts.astype(np.int32), pad_col=pad_col,
    )


@dataclasses.dataclass(frozen=True)
class ShardedBlocked:
    """BlockedRows partitioned for an n-way mesh: rows are assigned to
    shards contiguously (row r → shard r // rows_per_shard) and every
    shard's tiles are padded to the same count, so a leading-axis split
    over the mesh gives each device only tiles of its own rows —
    segment-sums stay device-local (no collectives in the reduce).

    Arrays have leading dim n_shards * blocks_per_shard, laid out
    shard-major; local_row is the tile's row id *within its shard*.
    """

    col: np.ndarray  # [S*Bs, L]
    val: np.ndarray
    mask: np.ndarray
    local_row: np.ndarray  # [S*Bs]
    counts: np.ndarray  # [S*Rs] nnz per row, shard-major, padded rows=0
    n_shards: int
    rows_per_shard: int
    n_rows: int  # logical (unpadded) row count

    @property
    def padded_rows(self) -> int:
        return self.n_shards * self.rows_per_shard


def shard_blocked(blocked: BlockedRows, n_shards: int,
                  rows_per_shard: int | None = None) -> ShardedBlocked:
    """Partition tiles onto shards by row ownership.

    ``rows_per_shard`` overrides the default ceil split — used by the
    model-sharded ALS path, which needs the padded row count
    (n_shards * rows_per_shard) to also divide the model axis so the same
    factor matrix can be row-sharded over either mesh axis.
    """
    S = int(n_shards)
    if rows_per_shard is None:
        rows_per_shard = (blocked.n_rows + S - 1) // S
    elif rows_per_shard * S < blocked.n_rows:
        raise ValueError(
            f"rows_per_shard={rows_per_shard} x {S} shards cannot hold "
            f"{blocked.n_rows} rows"
        )
    shard_of_block = blocked.block_row // rows_per_shard

    order = np.argsort(shard_of_block, kind="stable")
    col, val, mask = blocked.col[order], blocked.val[order], blocked.mask[order]
    block_row = blocked.block_row[order]
    shard_sorted = shard_of_block[order]

    per_shard = np.bincount(shard_sorted, minlength=S)
    Bs = max(int(per_shard.max()), 1)

    L = blocked.block_len
    col_p = np.full((S, Bs, L), blocked.pad_col, dtype=np.int32)
    val_p = np.zeros((S, Bs, L), dtype=np.float32)
    mask_p = np.zeros((S, Bs, L), dtype=np.float32)
    lrow_p = np.zeros((S, Bs), dtype=np.int32)

    shard_start = np.zeros(S + 1, dtype=np.int64)
    np.cumsum(per_shard, out=shard_start[1:])
    idx_in_shard = np.arange(block_row.shape[0]) - shard_start[shard_sorted]
    col_p[shard_sorted, idx_in_shard] = col
    val_p[shard_sorted, idx_in_shard] = val
    mask_p[shard_sorted, idx_in_shard] = mask
    lrow_p[shard_sorted, idx_in_shard] = (
        block_row - shard_sorted * rows_per_shard
    ).astype(np.int32)

    # Row r lives at global slot r (shard-major layout == row order).
    counts_p = np.zeros(S * rows_per_shard, dtype=np.int32)
    counts_p[: blocked.counts.shape[0]] = blocked.counts

    return ShardedBlocked(
        col=col_p.reshape(S * Bs, L),
        val=val_p.reshape(S * Bs, L),
        mask=mask_p.reshape(S * Bs, L),
        local_row=lrow_p.reshape(S * Bs),
        counts=counts_p,
        n_shards=S,
        rows_per_shard=rows_per_shard,
        n_rows=blocked.n_rows,
    )
