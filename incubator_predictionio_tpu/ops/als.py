"""Alternating Least Squares on a TPU mesh.

The reference's recommendation templates call MLlib's Spark ALS
(reference: examples/scala-parallel-recommendation — mllib ALS.train /
ALS.trainImplicit; the distributed in/out-block shuffle lives inside Spark,
SURVEY.md §2.9). This is a ground-up TPU design instead, following the ALX
recipe (PAPERS.md: arxiv 2112.02194):

- Factor matrices are dense f32 arrays. The side being *solved* is
  row-sharded over the mesh data axis; on a 1-D mesh the counterpart
  factor matrix is gathered (replicated) for the solve — the ICI
  all-gather replaces MLlib's factor shuffle.
- On a 2-D (d, m) mesh the counterpart is instead row-sharded over the
  MODEL_AXIS (the ALX sharded layout): each device gathers only rows it
  owns (zeros elsewhere) and the per-row normal equations — linear in
  per-entry outer products — are psummed over 'm'. HBM budget: factor
  storage per device is n_rows·k·4/m bytes instead of n_rows·k·4, so
  catalog capacity scales linearly with the model axis; e.g. 20M items
  at rank 128 is 10.2 GB replicated (over a v5e's 16 GB once both sides
  plus tiles are resident) but 1.3 GB/device on an m=8 ring. The extra
  cost is one [rows/d, k, k] psum per half-step plus the d↔m all-to-all
  that re-shards freshly solved factors.
- Ratings are laid out as blocked-COO tiles (ops/blocked.py), twice:
  user-major and item-major. Per-tile Gram matrices are batched einsums
  on the MXU; tile→row segment-sums are device-local by construction.
- One half-step solves the regularized normal equations
  (YᵀY + λ·c·I) x = Yᵀr per row with a batched Cholesky solve.
- The whole iteration loop runs inside one jit under shard_map; the only
  cross-device traffic is the all-gather of freshly solved factors.

Regularization conventions (must match template behaviour — SURVEY.md §7
"hard parts"): ``lambda_scaling='nratings'`` multiplies λ by the row's
rating count (ALS-WR, classic MLlib); ``'plain'`` uses λ directly
(Spark ≥1.4 default). Both supported; explicit and implicit feedback.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from .blocked import BlockedRows, ShardedBlocked, build_blocked, shard_blocked
from .pallas_kernels import batched_spd_solve
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, default_mesh


@dataclasses.dataclass(frozen=True)
class ALSParams:
    rank: int = 10
    num_iterations: int = 10
    reg: float = 0.01  # "lambda" in engine.json (reserved word in Python)
    lambda_scaling: str = "plain"  # 'plain' | 'nratings'
    implicit_prefs: bool = False
    alpha: float = 1.0  # implicit-feedback confidence weight
    seed: int = 3
    block_len: int = 32
    # "auto" → bfloat16 on a TPU mesh, float32 elsewhere. Explicit
    # "float32"/"bfloat16" override.
    compute_dtype: str = "auto"
    # Tiles processed per scan step inside a half-step. 0 = all at once
    # (small data). At ML-20M scale the per-tile gram intermediate
    # [B, k, k] would be ~10GB; chunking scans tile slabs and scatter-adds
    # into the per-row normal equations, capping live memory at
    # [chunk, L, k] + [chunk, k, k] + the [rows, k, k] accumulator.
    # -1 = auto: chunk only when the unchunked gram batch would exceed
    # the per-device budget (see _resolve_params).
    chunk_tiles: int = -1


@dataclasses.dataclass
class ALSFactors:
    user_factors: np.ndarray  # [n_users, k] f32 (host side after train)
    item_factors: np.ndarray  # [n_items, k]
    n_users: int
    n_items: int


def _grams_from_p(p, val, *, implicit, alpha, compute_dtype):
    """Per-tile normal-equation contributions from gathered counterpart
    rows p [B, L, k]: grams [B, k, k], rhs [B, k].

    Padding / non-owned slots must already be zero rows in p. Both sums
    are linear in per-entry outer products (each entry l contributes
    p_l·p_lᵀ resp. w_l·p_l), so zero rows contribute nothing — and
    shard-partial p's (each model shard zeroing rows it doesn't own)
    psum to exactly the full-gather result.
    """
    cd = compute_dtype
    if implicit:
        # Hu-Koren-Volinsky: A = YᵀY + Yᵀ(C-I)Y + λ·c·I, b = YᵀCp where
        # p=1 for observed. C-I = alpha·r on observed entries only.
        cw = (alpha * val)[..., None].astype(cd)  # confidence-1 weights
        w = 1.0 + alpha * val
        grams = jnp.einsum("blk,blm->bkm", p * cw, p,
                           preferred_element_type=jnp.float32)
        rhs = jnp.einsum("blk,bl->bk", p, w.astype(cd),
                         preferred_element_type=jnp.float32)
    else:
        grams = jnp.einsum("blk,blm->bkm", p, p,
                           preferred_element_type=jnp.float32)
        rhs = jnp.einsum("blk,bl->bk", p, val.astype(cd),
                         preferred_element_type=jnp.float32)
    return grams, rhs


def _gather_model_partial(y_local, col, compute_dtype):
    """ALX sharded gather: rows this shard owns, zero rows elsewhere.

    ``y_local`` is this device's row shard of the counterpart factor
    matrix ([rows_total / m, k], MODEL_AXIS-sharded, contiguous blocks in
    axis order). Column indices outside this shard's window — including
    the out-of-range padding index — gather exact zeros, so psumming any
    per-entry-linear reduction of the result over MODEL_AXIS equals the
    full-gather reduction without ever materializing the full matrix on
    one device (PAPERS.md ALX, arxiv 2112.02194 §3).
    """
    cd = compute_dtype
    rows_local = y_local.shape[0]
    off = jax.lax.axis_index(MODEL_AXIS) * rows_local
    lc = col - off
    valid = (lc >= 0) & (lc < rows_local)
    p = jnp.take(y_local, jnp.clip(lc, 0, rows_local - 1), axis=0)
    return p.astype(cd) * valid[..., None].astype(cd)


def _half_step_local(y, col, val, local_row, counts, yty, *,
                     rows_per_shard, reg, lambda_scaling, implicit, alpha,
                     compute_dtype, chunk_tiles=0, row_span=0,
                     platform=None, model_sharded=False):
    """Solve one side's factors for one shard's rows (runs inside
    shard_map; all arrays are the local shard).

    Replicated mode (``model_sharded=False``): ``y`` is the full
    counterpart matrix plus a trailing all-zero sentinel row that padding
    column indices resolve to.

    Model-sharded mode: ``y`` is this device's MODEL_AXIS row shard; the
    gather is partial (zeros for non-owned rows) and the per-row normal
    equations are psummed over MODEL_AXIS before the solve — the ALX
    sharded layout, so factor HBM scales with 1/m.
    """
    k = y.shape[1]
    n_tiles = col.shape[0]

    def gather(cols):
        if model_sharded:
            return _gather_model_partial(y, cols, compute_dtype)
        return y[cols].astype(compute_dtype)
    if chunk_tiles and n_tiles > chunk_tiles:
        # Large data: scan tile slabs. Tiles are row-sorted, so each
        # slab's rows fall in a contiguous window of at most ``row_span``
        # rows (host-computed static bound). The tile→row reduction is a
        # one-hot matmul on the MXU — orders of magnitude faster than an
        # XLA scatter-add at this size — and lands in the accumulator via
        # one contiguous dynamic-slice read-modify-write per slab.
        n_chunks = (n_tiles + chunk_tiles - 1) // chunk_tiles
        pad = n_chunks * chunk_tiles - n_tiles
        if pad:
            # Chunk padding: sentinel zero row of y (replicated mode) or
            # an index no model shard owns (sharded mode) — zeros either way.
            pad_idx = (np.int32(2**31 - 1) if model_sharded
                       else y.shape[0] - 1)
            col = jnp.pad(col, ((0, pad), (0, 0)), constant_values=pad_idx)
            val = jnp.pad(val, ((0, pad), (0, 0)))
            local_row = jnp.pad(local_row, (0, pad))
        cshape = (n_chunks, chunk_tiles)
        col_c = col.reshape(*cshape, -1)
        val_c = val.reshape(*cshape, -1)
        lrow_c = local_row.reshape(cshape)
        span = int(row_span)
        cd = compute_dtype
        span_iota = jnp.arange(span, dtype=jnp.int32)

        def scan_body(carry, chunk):
            a_acc, b_acc = carry
            ccol, cval, clrow = chunk
            grams, rhs = _grams_from_p(
                gather(ccol), cval,
                implicit=implicit, alpha=alpha, compute_dtype=cd,
            )
            # Window base: first tile's row. Tail padding tiles carry
            # lrow 0 and zero grams — they either miss the window
            # (local < 0) or add zeros, both harmless.
            rbase = clrow[0]
            local = clrow - rbase                       # [C] in [0, span)
            onehot = (local[None, :] == span_iota[:, None]).astype(cd)
            # f32 path must match segment_sum bitwise-closely: force full
            # f32 matmul precision (TPU default truncates f32 to bf16 on
            # the MXU, which the non-chunked path never does).
            #
            # bf16 path — DELIBERATE precision divergence from unchunked:
            # grams are f32 (accumulated from bf16 factors) but are cast
            # back to bf16 here so the one-hot tile→row reduction runs as
            # a bf16 MXU matmul; the unchunked path segment-sums the f32
            # grams directly. The reduction dominates this path's FLOPs
            # (span·chunk·k² vs the gram's chunk·L·k²), so an f32-HIGHEST
            # reduction would cost ~6× the whole half-step. Per-entry
            # rounding is one bf16 ulp (rel ≤ 2^-8) BEFORE an f32
            # accumulation, and the λ ridge keeps the solve conditioned;
            # tests/test_als_chunked_bf16.py bounds the chunked-vs-
            # unchunked factor disagreement under this scheme.
            prec = (None if cd == jnp.bfloat16
                    else jax.lax.Precision.HIGHEST)
            part_a = jnp.einsum(
                "rc,ckm->rkm", onehot, grams.astype(cd),
                preferred_element_type=jnp.float32, precision=prec,
            )
            part_b = jnp.einsum(
                "rc,ck->rk", onehot, rhs.astype(cd),
                preferred_element_type=jnp.float32, precision=prec,
            )
            a_win = jax.lax.dynamic_slice(
                a_acc, (rbase, 0, 0), (span, k, k))
            b_win = jax.lax.dynamic_slice(b_acc, (rbase, 0), (span, k))
            a_acc = jax.lax.dynamic_update_slice(
                a_acc, a_win + part_a, (rbase, 0, 0))
            b_acc = jax.lax.dynamic_update_slice(
                b_acc, b_win + part_b, (rbase, 0))
            return (a_acc, b_acc), None

        # Accumulators padded by `span` rows so the last window fits.
        a0 = jnp.zeros((rows_per_shard + span, k, k), jnp.float32)
        b0 = jnp.zeros((rows_per_shard + span, k), jnp.float32)
        if hasattr(jax.lax, "pcast"):
            # Inside shard_map the scatter-add output is device-varying;
            # mark the zero carries to match (jax ≥0.8 VMA tracking). In
            # sharded mode partial grams also vary over MODEL_AXIS until
            # the psum below.
            vaxes = (DATA_AXIS,) + ((MODEL_AXIS,) if model_sharded else ())
            a0 = jax.lax.pcast(a0, vaxes, to="varying")
            b0 = jax.lax.pcast(b0, vaxes, to="varying")
        (a, b), _ = jax.lax.scan(
            scan_body, (a0, b0), (col_c, val_c, lrow_c)
        )
        a = a[:rows_per_shard]
        b = b[:rows_per_shard]
    else:
        grams, rhs = _grams_from_p(
            gather(col), val,
            implicit=implicit, alpha=alpha, compute_dtype=compute_dtype,
        )
        a = jax.ops.segment_sum(grams, local_row, num_segments=rows_per_shard)
        b = jax.ops.segment_sum(rhs, local_row, num_segments=rows_per_shard)
    if model_sharded:
        # Reconstruct the full per-row normal equations from the shard
        # partials — the one collective of the sharded gather. Placed on
        # the [rows/d, k, k] accumulators (cheaper than psumming gathered
        # [chunk, L, k] factors every scan step at ml20m shapes).
        a = jax.lax.psum(a, MODEL_AXIS)
        b = jax.lax.psum(b, MODEL_AXIS)
    if implicit:
        a = a + yty[None, :, :]  # shared YᵀY term (all items)

    if lambda_scaling == "nratings":
        lam = reg * jnp.maximum(counts.astype(jnp.float32), 1.0)
    else:
        lam = jnp.full(counts.shape, reg, dtype=jnp.float32)
    # Rows with no ratings keep a well-conditioned system (solution 0).
    lam = lam + jnp.where(counts == 0, 1e-6, 0.0)
    a = a + lam[:, None, None] * jnp.eye(k, dtype=jnp.float32)

    # Batched SPD solve: Pallas VMEM Gauss-Jordan on TPU (43x the XLA
    # batched-Cholesky lowering at ml20m shape), XLA Cholesky elsewhere.
    # platform is the MESH's device platform, threaded from the caller —
    # jax.default_backend() is wrong here: the driver dry-runs a CPU mesh
    # while a TPU is still the process default backend (and vice versa in
    # tests), and pallas_call on CPU without interpret mode is an error.
    x = batched_spd_solve(a, b, vma=(DATA_AXIS,), platform=platform)
    return x.astype(jnp.float32)


def _chunk_row_span(sb: ShardedBlocked, chunk_tiles: int) -> int:
    """Static bound on how many distinct rows one scan slab can touch.

    Mirrors the per-device chunking in ``_half_step_local``: each shard's
    local tiles are padded to a multiple of chunk_tiles and sliced; tiles
    are row-sorted, so a slab's rows live in [first_row, max_row]. Returns
    the max such window, rounded up to a lane-friendly multiple of 128.
    """
    local_tiles = sb.col.shape[0] // sb.n_shards
    if not chunk_tiles or local_tiles <= chunk_tiles:
        return 0
    lrow = sb.local_row.reshape(sb.n_shards, local_tiles)
    n_chunks = (local_tiles + chunk_tiles - 1) // chunk_tiles
    pad = n_chunks * chunk_tiles - local_tiles
    if pad:
        lrow = np.pad(lrow, ((0, 0), (0, pad)))
    chunks = lrow.reshape(sb.n_shards, n_chunks, chunk_tiles)
    span = int(
        np.maximum(chunks.max(axis=2) - chunks[:, :, 0], 0).max()
    ) + 1
    return min(-(-span // 128) * 128, sb.rows_per_shard + 128)


# Per-device budget for the unchunked [tiles, k, k] f32 gram batch plus
# the gathered [tiles, L, k] factors; above it the scan-chunked path kicks
# in. 1 GiB leaves headroom for factors + tiles + accumulators on a 16 GB
# v5e chip.
_AUTO_CHUNK_BUDGET_BYTES = 1 << 30
# Measured sweet spot at ml20m/rank32 on v5e (bench.py sweeps): big enough
# to keep the one-hot MXU reduction and DMA pipeline fed, small enough
# that the [chunk, L, k] + [chunk, k, k] slabs stay cheap.
_AUTO_CHUNK_TILES = 2048


def _resolve_params(mesh: Mesh, params: ALSParams, users: ShardedBlocked,
                    items: ShardedBlocked) -> ALSParams:
    """Materialize 'auto' knobs against the actual mesh + data layout.

    Templates ship compute_dtype="auto" / chunk_tiles=-1 so a plain
    `pio train` picks the TPU-optimal configuration the benchmarks use —
    bf16 gathers on TPU meshes and scan-chunking whenever the unchunked
    per-tile intermediates would blow the HBM budget (ml20m would
    otherwise build a ~10 GB gram batch and OOM).
    """
    cd = params.compute_dtype
    if cd == "auto":
        platform = mesh.devices.flat[0].platform
        cd = "bfloat16" if platform == "tpu" else "float32"
    chunk = params.chunk_tiles
    if chunk < 0:
        k = params.rank
        L = users.col.shape[1]
        cd_bytes = 2 if cd == "bfloat16" else 4
        per_tile = L * k * cd_bytes + k * k * 4
        tiles_local = max(users.col.shape[0] // users.n_shards,
                          items.col.shape[0] // items.n_shards)
        if tiles_local * per_tile <= _AUTO_CHUNK_BUDGET_BYTES:
            chunk = 0
        else:
            # Cap by the budget too: at extreme rank/block_len a 2048-tile
            # slab can itself exceed the budget, and over-budget data
            # guarantees budget//per_tile < tiles_local, so the chunked
            # path (n_tiles > chunk_tiles) always engages.
            chunk = max(1, min(_AUTO_CHUNK_TILES,
                               _AUTO_CHUNK_BUDGET_BYTES // per_tile))
    if cd != params.compute_dtype or chunk != params.chunk_tiles:
        params = dataclasses.replace(
            params, compute_dtype=cd, chunk_tiles=chunk)
    return params


def _make_train_fn(mesh: Mesh, params: ALSParams, users: ShardedBlocked,
                   items: ShardedBlocked, span_override=None):
    """Build the jitted full training loop for fixed layouts.

    ``span_override`` = (u_span, i_span): sharded multi-host ingest
    passes globally-maxed scan-window bounds here, because each process
    only holds its own tiles and the spans are baked into the (identical
    everywhere) executable. All other layout numbers are per-shard and
    already process-invariant.
    """
    params = _resolve_params(mesh, params, users, items)
    cd = jnp.bfloat16 if params.compute_dtype == "bfloat16" else jnp.float32
    implicit = params.implicit_prefs
    # Kernel selection must follow the MESH's platform, not the process
    # default backend: the driver validates multi-chip sharding on a
    # virtual CPU mesh while the sandbox TPU stays the default backend.
    mesh_platform = mesh.devices.flat[0].platform
    # 2-D (d, m) mesh → ALX factor sharding: the counterpart factor
    # matrix is row-sharded over MODEL_AXIS (HBM per device ∝ 1/m) and
    # the per-row normal equations are psummed from shard partials.
    model_sharded = MODEL_AXIS in mesh.axis_names

    row_spec = P(DATA_AXIS)          # tiles / rows split over data axis
    rep = P()                        # replicated
    y_spec = P(MODEL_AXIS, None) if model_sharded else rep

    if span_override is not None:
        u_span, i_span = span_override
    else:
        u_span = _chunk_row_span(users, params.chunk_tiles)
        i_span = _chunk_row_span(items, params.chunk_tiles)

    def one_side(y, blk_cols, blk_vals, blk_lrow, counts,
                 rows_per_shard, row_span):
        if model_sharded:
            # No sentinel: the sharded gather masks by ownership window,
            # and padded row counts already divide the model axis.
            y_cd = jax.lax.with_sharding_constraint(
                y.astype(cd), NamedSharding(mesh, y_spec))
        else:
            # Sentinel zero row appended so padding column indices gather
            # 0s (mask-free hot loop); cast once here so the scan gathers
            # half-width bf16 rows instead of f32.
            y_cd = jnp.concatenate(
                [y, jnp.zeros((1, y.shape[1]), y.dtype)], axis=0
            ).astype(cd)
        yty = (
            jnp.einsum("nk,nm->km", y_cd, y_cd,
                       preferred_element_type=jnp.float32)
            if implicit
            else jnp.zeros((params.rank, params.rank), jnp.float32)
        )
        fn = shard_map(
            functools.partial(
                _half_step_local,
                rows_per_shard=rows_per_shard,
                reg=params.reg,
                lambda_scaling=params.lambda_scaling,
                implicit=implicit,
                alpha=params.alpha,
                compute_dtype=cd,
                chunk_tiles=params.chunk_tiles,
                row_span=row_span,
                platform=mesh_platform,
                model_sharded=model_sharded,
            ),
            mesh=mesh,
            in_specs=(y_spec, row_spec, row_spec, row_spec, row_spec, rep),
            out_specs=row_spec,
        )
        x = fn(y_cd, blk_cols, blk_vals, blk_lrow, counts, yty)
        if model_sharded:
            # Solved rows leave the shard_map split over 'd'; re-shard to
            # the MODEL_AXIS storage layout (XLA all-to-all over ICI) so
            # the next half-step consumes it as a sharded counterpart.
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, y_spec))
        return x

    u_rps, i_rps = users.rows_per_shard, items.rows_per_shard

    # The big tile arrays enter as jit args (not baked-in constants), and
    # n_iters is traced so one compilation serves full runs, checkpoint
    # chunks, and resume remainders alike (fori_loop with a traced bound
    # lowers to while_loop — fine on TPU, no unrolling wanted here).
    def loop(n_iters, x0, y0, u_col, u_val, u_lrow, u_counts,
             i_col, i_val, i_lrow, i_counts):
        def body(_, carry):
            x, y = carry
            x = one_side(y, u_col, u_val, u_lrow, u_counts, u_rps, u_span)
            y = one_side(x, i_col, i_val, i_lrow, i_counts, i_rps, i_span)
            return (x, y)

        return jax.lax.fori_loop(0, n_iters, body, (x0, y0))

    shardings = {
        "row2": NamedSharding(mesh, P(DATA_AXIS, None)),
        "row1": NamedSharding(mesh, P(DATA_AXIS)),
        "rep": NamedSharding(mesh, P()),
        "factors": NamedSharding(mesh, y_spec),
    }
    in_shardings = (
        shardings["rep"],
        shardings["factors"], shardings["factors"],
        shardings["row2"], shardings["row2"],
        shardings["row1"], shardings["row1"],
        shardings["row2"], shardings["row2"],
        shardings["row1"], shardings["row1"],
    )
    # Outputs stay MODEL_AXIS-sharded on a 2-D mesh — replicating here
    # would all-gather both full factor matrices onto every device and
    # defeat the 1/m HBM scaling (host device_get assembles from shards).
    # Multi-controller runs need replicated outputs so every process can
    # device_get its result.
    out_s = (shardings["factors"] if jax.process_count() == 1
             else shardings["rep"])
    fitted = jax.jit(
        loop,
        in_shardings=in_shardings,
        out_shardings=(out_s, out_s),
    )
    return fitted, in_shardings


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Optional[Mesh] = None,
    checkpoint_hook=None,
    resume: bool = False,
    timings: Optional[dict] = None,
) -> ALSFactors:
    """Train explicit/implicit ALS from a COO rating triple.

    ``checkpoint_hook`` (workflow.checkpoint.CheckpointHook): when enabled,
    the loop runs in hook.every_n-iteration chunks through the SAME jitted
    executable (n_iters is traced — zero recompiles) and snapshots the
    factor pytree at each chunk boundary; ``resume=True`` restores the
    latest snapshot and runs only the remaining iterations. Chunking is
    bitwise-identical math to the single fori_loop. The reference cannot do
    this at all — a failed Spark ALS job restarts from zero (SURVEY.md §5.4).

    ``timings``: pass a dict to get the bench-grade phase breakdown
    (upload / compile / steady-state device seconds, with the scalar-
    readback completion barrier that the remote-PJRT tunnel requires —
    block_until_ready can return early through it). This is how bench.py
    measures the REAL product path: `pio train` → Engine.train →
    ALSAlgorithm → here. Single-process, non-checkpoint-chunked runs only.
    """
    mesh = mesh or default_mesh()
    if DATA_AXIS not in mesh.axis_names:
        raise ValueError(f"mesh must have a '{DATA_AXIS}' axis, "
                         f"got {mesh.axis_names}")
    # Tiles (and the rows being solved) split over the data axis; on a
    # 2-D (d, m) mesh the factor matrices are additionally row-sharded
    # over the model axis (ALX layout), so padded row counts must divide
    # both axes.
    d_size = mesh.shape[DATA_AXIS]
    m_size = mesh.shape.get(MODEL_AXIS, 1)

    def _rows_per_shard(n_rows: int) -> int:
        rps = -(-n_rows // d_size)
        return -(-rps // m_size) * m_size

    rps_users = _rows_per_shard(n_users)
    rps_items = _rows_per_shard(n_items)
    # Padding column indices point one past the counterpart's padded rows:
    # in replicated mode one_side appends a zero sentinel row there (mask-
    # free hot loop); in sharded mode the index falls outside every
    # shard's ownership window and gathers zeros via the validity mask.
    pad_items = d_size * rps_items
    pad_users = d_size * rps_users
    by_user = shard_blocked(
        build_blocked(user_idx, item_idx, rating, n_users, params.block_len,
                      pad_col=pad_items), d_size, rows_per_shard=rps_users
    )
    by_item = shard_blocked(
        build_blocked(item_idx, user_idx, rating, n_items, params.block_len,
                      pad_col=pad_users), d_size, rows_per_shard=rps_items
    )

    k = params.rank
    x_shape = (by_user.padded_rows, k)
    y_shape = (by_item.padded_rows, k)

    def _fresh_init():
        # MLlib-style init: scaled standard normal.
        rng = np.random.default_rng(params.seed)
        x = (rng.standard_normal(x_shape) / np.sqrt(k)).astype(np.float32)
        y = (rng.standard_normal(y_shape) / np.sqrt(k)).astype(np.float32)
        return x, y

    # Fingerprint of the exact COO triple: resume is only sound against the
    # identical rating data (shape equality alone misses in-place rating
    # updates that keep n_users/n_items fixed). Only computed when a hook
    # is active — it's O(nnz) hashing that plain trains shouldn't pay.
    fingerprint = None
    if checkpoint_hook is not None:
        import zlib

        fingerprint = zlib.crc32(
            rating.astype(np.float32, copy=False).tobytes(),
            zlib.crc32(np.asarray(item_idx).tobytes(),
                       zlib.crc32(np.asarray(user_idx).tobytes())))

    start_iter = 0
    x0 = y0 = None
    if checkpoint_hook is not None and resume:
        from ..workflow.checkpoint import CheckpointIncompatibleError

        step = checkpoint_hook.latest_step()
        if step is not None and step < params.num_iterations:
            start_iter, tree = checkpoint_hook.restore(step)
            rx, ry = np.asarray(tree["user_factors"]), np.asarray(tree["item_factors"])
            if rx.shape != x_shape or ry.shape != y_shape:
                raise CheckpointIncompatibleError(
                    f"checkpoint shapes {rx.shape}/{ry.shape} do not match the "
                    f"current data layout {x_shape}/{y_shape}; the event data "
                    "changed since the interrupted run — retrain from scratch"
                )
            saved_fp = int(np.asarray(tree.get("fingerprint", -1)))
            if saved_fp != fingerprint:
                raise CheckpointIncompatibleError(
                    "checkpoint was written against different rating data "
                    "(fingerprint mismatch); the event store changed since "
                    "the interrupted run — retrain from scratch"
                )
            x0, y0 = rx, ry
        elif step is not None:
            # Snapshots are never written at the final iteration, so a
            # checkpoint at step >= num_iterations means the params changed
            # (num_iterations lowered) since the interrupted run.
            raise CheckpointIncompatibleError(
                f"latest checkpoint is at iteration {step} but only "
                f"{params.num_iterations} iterations were requested; the "
                "snapshot is from a run with more iterations — retrain from "
                "scratch or raise num_iterations"
            )

    if x0 is None:
        x0, y0 = _fresh_init()
    fn, in_shardings = _make_train_fn(mesh, params, by_user, by_item)
    blocks = (
        by_user.col, by_user.val, by_user.local_row, by_user.counts,
        by_item.col, by_item.val, by_item.local_row, by_item.counts,
    )
    if jax.process_count() > 1:
        # Multi-controller: every process holds the SAME full numpy
        # arrays (the event store is shared), so build global jax.Arrays
        # explicitly — jit refuses sharded numpy inputs across processes.
        def _globalize(host, sharding):
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )

        x0 = _globalize(np.asarray(x0), in_shardings[1])
        y0 = _globalize(np.asarray(y0), in_shardings[2])
        blocks = tuple(
            _globalize(np.asarray(b), s)
            for b, s in zip(blocks, in_shardings[3:])
        )
    chunk = checkpoint_hook.every_n if checkpoint_hook is not None and checkpoint_hook.enabled else 0
    if (timings is not None and jax.process_count() == 1
            and not (chunk and params.num_iterations - start_iter > chunk)):
        import time as _time

        t0 = _time.perf_counter()
        dx0 = jax.device_put(np.asarray(x0), in_shardings[1])
        dy0 = jax.device_put(np.asarray(y0), in_shardings[2])
        dev_blocks = tuple(
            jax.device_put(np.asarray(b), s)
            for b, s in zip(blocks, in_shardings[3:])
        )
        jax.block_until_ready((dx0, dy0, dev_blocks))
        timings["upload_seconds"] = _time.perf_counter() - t0

        n = np.int32(params.num_iterations - start_iter)
        t0 = _time.perf_counter()
        compiled = fn.lower(n, dx0, dy0, *dev_blocks).compile()
        timings["compile_seconds"] = _time.perf_counter() - t0

        # Warm-up dispatch (n_iters is traced: same executable, zero work),
        # then the timed run with a scalar readback as the completion
        # barrier — through the remote-PJRT tunnel block_until_ready can
        # return before the device finishes, a device_get cannot.
        warm = compiled(np.int32(0), dx0, dy0, *dev_blocks)
        _ = jax.device_get(warm[0][:1, :1])
        t0 = _time.perf_counter()
        x, y = compiled(n, dx0, dy0, *dev_blocks)
        _ = jax.device_get(x[:1, :1])
        timings["device_train_seconds"] = _time.perf_counter() - t0
    elif chunk and params.num_iterations - start_iter > chunk:
        x, y = x0, y0
        it = start_iter
        while it < params.num_iterations:
            n = min(chunk, params.num_iterations - it)
            x, y = fn(n, x, y, *blocks)
            it += n
            if it < params.num_iterations:
                checkpoint_hook.save(
                    it, {"user_factors": x, "item_factors": y,
                         "fingerprint": np.int64(fingerprint)}
                )
    else:
        x, y = fn(params.num_iterations - start_iter, x0, y0, *blocks)
    x, y = jax.device_get((x, y))
    return ALSFactors(
        user_factors=np.asarray(x)[:n_users],
        item_factors=np.asarray(y)[:n_items],
        n_users=n_users,
        n_items=n_items,
    )


def process_row_ranges(n_rows: int, mesh: Optional[Mesh] = None
                       ) -> tuple[int, int]:
    """[row0, row1) of entity rows THIS process owns on the mesh data axis.

    The contract for sharded multi-host ingest: each training process
    range-reads only the events whose solved-side row falls in its range
    (one range per side), instead of every host scanning the full store.
    Deterministic from (n_rows, mesh) alone — no coordination needed.
    """
    mesh = mesh or default_mesh()
    d_size = mesh.shape[DATA_AXIS]
    m_size = mesh.shape.get(MODEL_AXIS, 1)
    rps = -(-(-(-n_rows // d_size)) // m_size) * m_size
    n_proc = jax.process_count()
    if d_size % n_proc:
        # Same contract train_als_process_sharded enforces; failing here
        # keeps callers from range-reading wrong slices before train raises.
        raise ValueError(
            f"data axis size {d_size} is not divisible by "
            f"{n_proc} processes")
    shards_per_proc = d_size // n_proc
    p = jax.process_index()
    return p * shards_per_proc * rps, (p + 1) * shards_per_proc * rps


def _local_blocked(rows, cols, vals, row0, n_local_rows, rps, n_local_shards,
                   block_len, pad_col):
    """Blocked tiles for this process's row range only. ``rows`` are
    global indices, all within [row0, row0 + n_local_rows)."""
    rows = np.asarray(rows, dtype=np.int64)
    if rows.size and (rows.min() < row0 or rows.max() >= row0 + n_local_rows):
        raise ValueError(
            f"sharded ingest: got rows outside this process's range "
            f"[{row0}, {row0 + n_local_rows}) — the caller must range-read "
            "only owned rows (process_row_ranges)")
    blocked = build_blocked(rows - row0, cols, vals, n_local_rows,
                            block_len, pad_col=pad_col)
    return shard_blocked(blocked, n_local_shards, rows_per_shard=rps)


def train_als_process_sharded(
    user_slice: tuple[np.ndarray, np.ndarray, np.ndarray],
    item_slice: tuple[np.ndarray, np.ndarray, np.ndarray],
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Optional[Mesh] = None,
) -> ALSFactors:
    """Multi-controller ALS where each process ingests ONLY its shard.

    ``user_slice`` = (user_idx, item_idx, rating) holding exactly the
    events whose USER row this process owns (``process_row_ranges(
    n_users)``); ``item_slice`` the same for ITEM rows. In a deployment
    these are two range-reads against the shared event store — no host
    ever materializes the full dataset, removing train_als's
    every-process-holds-everything constraint (the Spark-side analog is
    partitioned RDD ingest, SURVEY.md §2.10).

    The math and layout are IDENTICAL to ``train_als`` on the same
    global data: tiles are built per-owned-shard in local coordinates,
    padded to the global per-shard tile count (one tiny allgather of
    tile counts — the only control-plane coordination), and assembled
    with ``jax.make_array_from_process_local_data``. Factors match the
    single-process run bit-for-bit.

    1-D (data-axis) meshes; checkpoint hooks are not supported here yet.
    """
    mesh = mesh or default_mesh()
    if MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1:
        raise ValueError(
            "sharded ingest currently supports 1-D data meshes only")
    d_size = mesh.shape[DATA_AXIS]
    n_proc = jax.process_count()
    if d_size % n_proc:
        raise ValueError(f"{d_size} devices do not divide {n_proc} processes")
    n_local = d_size // n_proc

    rps_u = -(-n_users // d_size)
    rps_i = -(-n_items // d_size)
    pad_users, pad_items = d_size * rps_u, d_size * rps_i

    u_row0, _ = process_row_ranges(n_users, mesh)
    i_row0, _ = process_row_ranges(n_items, mesh)
    uu, ui, ur = user_slice
    iu, ii, ir = item_slice
    by_user = _local_blocked(uu, ui, ur, u_row0, n_local * rps_u, rps_u,
                             n_local, params.block_len, pad_col=pad_items)
    by_item = _local_blocked(ii, iu, ir, i_row0, n_local * rps_i, rps_i,
                             n_local, params.block_len, pad_col=pad_users)

    # Global per-shard tile count = max over every process's shards; the
    # one piece of global knowledge the layout needs. 2-int allgather
    # over the DCN control plane.
    from jax.experimental import multihost_utils

    local_bs = np.array([by_user.col.shape[0] // n_local,
                         by_item.col.shape[0] // n_local], np.int64)
    all_bs = np.asarray(
        multihost_utils.process_allgather(local_bs)).reshape(-1, 2)
    bs_u, bs_i = int(all_bs[:, 0].max()), int(all_bs[:, 1].max())

    def _pad_tiles(sb: ShardedBlocked, bs: int, pad_col: int):
        cur = sb.col.shape[0] // sb.n_shards
        if cur == bs:
            return sb
        L = sb.col.shape[1]

        def pad3(a, fill):
            a = a.reshape(sb.n_shards, cur, *a.shape[1:])
            width = [(0, 0), (0, bs - cur)] + [(0, 0)] * (a.ndim - 2)
            return np.pad(a, width, constant_values=fill).reshape(
                sb.n_shards * bs, *a.shape[2:])

        return dataclasses.replace(
            sb, col=pad3(sb.col, pad_col), val=pad3(sb.val, 0.0),
            mask=pad3(sb.mask, 0.0), local_row=pad3(sb.local_row, 0),
        )

    by_user = _pad_tiles(by_user, bs_u, pad_items)
    by_item = _pad_tiles(by_item, bs_i, pad_users)

    # Per-shard layout numbers (rows/tiles per shard, L) are identical
    # on every process after the padding above, so the local
    # ShardedBlocked describes the global layout — except the chunked-
    # scan row-span bounds, which are maxima over ALL shards: allgather
    # them so each process bakes the same executable.
    params = _resolve_params(mesh, params, by_user, by_item)
    spans = np.array([
        _chunk_row_span(by_user, params.chunk_tiles),
        _chunk_row_span(by_item, params.chunk_tiles),
    ], np.int64)
    all_spans = np.asarray(
        multihost_utils.process_allgather(spans)).reshape(-1, 2)
    span_override = (int(all_spans[:, 0].max()), int(all_spans[:, 1].max()))
    fn, in_shardings = _make_train_fn(mesh, params, by_user, by_item,
                                      span_override=span_override)

    # Same init as train_als._fresh_init — bit-for-bit parity. Factor
    # init is O(rows·k) host memory (tiny next to the event data, which
    # IS process-local here).
    k = params.rank
    rng = np.random.default_rng(params.seed)
    x0 = (rng.standard_normal((pad_users, k)) / np.sqrt(k)).astype(np.float32)
    y0 = (rng.standard_normal((pad_items, k)) / np.sqrt(k)).astype(np.float32)

    def _from_local(local, sharding, global_rows):
        return jax.make_array_from_process_local_data(
            sharding, local, (global_rows,) + local.shape[1:])

    u_blocks = (by_user.col, by_user.val, by_user.local_row,
                by_user.counts)
    i_blocks = (by_item.col, by_item.val, by_item.local_row,
                by_item.counts)
    blocks = tuple(
        _from_local(b, s, d_size * (b.shape[0] // n_local))
        for b, s in zip(u_blocks + i_blocks, in_shardings[3:])
    )
    # Factor carries are replicated on a 1-D mesh: every process supplies
    # the (identical, same-seed) full array.
    gx0 = jax.make_array_from_callback(
        x0.shape, in_shardings[1], lambda idx: x0[idx])
    gy0 = jax.make_array_from_callback(
        y0.shape, in_shardings[2], lambda idx: y0[idx])
    x, y = fn(np.int32(params.num_iterations), gx0, gy0, *blocks)
    x, y = jax.device_get((x, y))
    return ALSFactors(
        user_factors=np.asarray(x)[:n_users],
        item_factors=np.asarray(y)[:n_items],
        n_users=n_users,
        n_items=n_items,
    )


def predict_rmse(factors: ALSFactors, user_idx, item_idx, rating) -> float:
    """Host-side RMSE over a COO triple (eval helper)."""
    x = factors.user_factors[np.asarray(user_idx)]
    y = factors.item_factors[np.asarray(item_idx)]
    pred = np.sum(x * y, axis=1)
    err = pred - np.asarray(rating, dtype=np.float32)
    return float(np.sqrt(np.mean(err**2)))
