"""Alternating Least Squares on a TPU mesh.

The reference's recommendation templates call MLlib's Spark ALS
(reference: examples/scala-parallel-recommendation — mllib ALS.train /
ALS.trainImplicit; the distributed in/out-block shuffle lives inside Spark,
SURVEY.md §2.9). This is a ground-up TPU design instead, following the ALX
recipe (PAPERS.md: arxiv 2112.02194):

- Ratings are laid out as length-bucketed dense row slabs
  (ops/rowblocks.py): each row's entries occupy one [C_b]-wide slab row,
  so the per-row normal equations fall straight out of a batched
  [R, C_b, k] einsum on the MXU — there is no tile→row segment reduction
  at all. The layout minimizes padded entries because the half-step is
  GATHER-BOUND: the TPU gather unit sustains a fixed ~420M rows/s
  (measured, tools/profile_als.py), so every padded entry wastes a fixed
  gather slot. See BASELINE.md "ALS half-step roofline".
- Factor matrices are dense f32 arrays in layout ("π") order. The side
  being *solved* is slot-sharded over the mesh data axis; on a 1-D mesh
  the counterpart factor matrix is replicated for the gather. On a 2-D
  (d, m) mesh the counterpart is instead row-sharded over MODEL_AXIS
  (the ALX sharded layout): each device gathers only slots it owns
  (zeros elsewhere) and the per-row normal equations — linear in
  per-entry outer products — are psummed over 'm'. HBM budget: factor
  storage per device is n_rows·k·4/m bytes, so catalog capacity scales
  linearly with the model axis. Ownership windows are windows of SLOTS,
  so the ALX layout composes with any data-axis layout (including
  multi-host sharded ingest) with no extra machinery.
- One half-step solves the regularized normal equations
  (YᵀY + λ·c·I) x = Yᵀr per row with a batched Pallas Gauss-Jordan
  solve (ops/pallas_kernels.py).
- The whole iteration loop runs inside one jit under shard_map; the only
  cross-device traffic is the counterpart replication (1-D) or, on 2-D
  meshes, per-chunk normal-equation psums (one [512, k, k] all-reduce
  per fused solve chunk — same total bytes as a single big psum, more
  latency points; the price of never materializing the normal
  equations) + the factor re-shard.

Regularization conventions (must match template behaviour — SURVEY.md §7
"hard parts"): ``lambda_scaling='nratings'`` multiplies λ by the row's
rating count (ALS-WR, classic MLlib); ``'plain'`` uses λ directly
(Spark ≥1.4 default). Both supported; explicit and implicit feedback.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..common.faultinject import fault_point
from ..common.jax_compat import shard_map
from ..parallel import supervisor as gang

from .pallas_kernels import batched_spd_solve
from .rowblocks import (
    BucketArrays, LayoutPlan, fill_buckets, ladder_growth, plan_and_fill_both,
    plan_layout,
)
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, default_mesh, fast_put


@dataclasses.dataclass(frozen=True)
class ALSParams:
    rank: int = 10
    num_iterations: int = 10
    reg: float = 0.01  # "lambda" in engine.json (reserved word in Python)
    lambda_scaling: str = "plain"  # 'plain' | 'nratings'
    implicit_prefs: bool = False
    alpha: float = 1.0  # implicit-feedback confidence weight
    seed: int = 3
    # Retained for engine.json compatibility (blockLen): the bucketed
    # layout has no tiles, so this only scales the chunk_tiles budget
    # below (chunk_tiles × block_len = gathered entries per device step).
    block_len: int = 32
    # "auto" → bfloat16 on a TPU mesh, float32 elsewhere. Explicit
    # "float32"/"bfloat16" override.
    compute_dtype: str = "auto"
    # Device-step granularity: each bucket's gather+gram(+solve) slab is
    # chunked to ≈ chunk_tiles × block_len gathered entries per step,
    # bounding the live [chunk, C_b, k] intermediate. -1 OR 0 = auto
    # (the fused pipeline targets 512-row chunks — the Pallas solve's
    # native slab width — capped at ~0.5 GB of gathered slab; chunking
    # never changes the math in this layout, so there is no "unchunked"
    # mode to ask for); engine.json's chunkTiles maps here and an
    # explicit value bounds the fused slab too.
    chunk_tiles: int = -1
    # All-ones ratings (implicit view/buy streams): the value slabs are
    # fully derivable on device, so train_als skips building/uploading
    # them — about half the host→device slab bytes. None = auto-detect
    # from the data; False forces the explicit-value path (tests).
    binary_ratings: "bool | None" = None


@dataclasses.dataclass
class ALSFactors:
    user_factors: np.ndarray  # [n_users, k] f32 (host side after train)
    item_factors: np.ndarray  # [n_items, k]
    n_users: int
    n_items: int


_AUTO_ENTRIES_PER_STEP = 1 << 17

# Checkpoint-fingerprint seed identifying the factor-storage layout
# ("π"/slot order, ops/rowblocks.py). Bump when the layout changes so
# snapshots from an older layout are rejected deterministically instead
# of resuming permuted factors when shapes happen to coincide.
_LAYOUT_TAG = 0x70_10_00_02


def _resolve_params(mesh: Mesh, params: ALSParams) -> tuple[ALSParams, int]:
    """Materialize 'auto' knobs; returns (params, entries_per_step)."""
    cd = params.compute_dtype
    if cd == "auto":
        platform = mesh.devices.flat[0].platform
        cd = "bfloat16" if platform == "tpu" else "float32"
        params = dataclasses.replace(params, compute_dtype=cd)
    if params.chunk_tiles > 0:
        entries = max(params.chunk_tiles * max(params.block_len, 1), 8)
    else:
        entries = _AUTO_ENTRIES_PER_STEP
    return params, entries


def _grams_rows(p, val, *, implicit, alpha, compute_dtype):
    """Per-row normal-equation contributions from gathered counterpart
    rows p [R, C, k]: grams [R, k, k] f32, rhs [R, k] f32.

    Padding / non-owned slots must already be zero rows in p. Both sums
    are linear in per-entry outer products, so zero rows contribute
    nothing — and shard-partial p's (each model shard zeroing slots it
    doesn't own) psum to exactly the full-gather result.

    ``val=None``: binary-ratings mode — every real entry is 1.0, so the
    per-entry weights collapse to scalars and no value slab ever exists
    (not even as a device-side ones array: a materialized ones slab
    would re-spend in HBM reads exactly the bytes the upload elision
    saved).
    """
    cd = compute_dtype
    if implicit:
        # Hu-Koren-Volinsky: A = YᵀY + Yᵀ(C-I)Y + λ·c·I, b = YᵀCp where
        # p=1 for observed. C-I = alpha·r on observed entries only.
        if val is None:
            grams = jnp.einsum("rck,rcm->rkm", p * jnp.asarray(alpha, cd), p,
                               preferred_element_type=jnp.float32)
            rhs = (1.0 + alpha) * jnp.sum(p, axis=1,
                                          dtype=jnp.float32)
        else:
            cw = (alpha * val)[..., None].astype(cd)  # confidence-1 weights
            w = 1.0 + alpha * val
            grams = jnp.einsum("rck,rcm->rkm", p * cw, p,
                               preferred_element_type=jnp.float32)
            rhs = jnp.einsum("rck,rc->rk", p, w.astype(cd),
                             preferred_element_type=jnp.float32)
    else:
        grams = jnp.einsum("rck,rcm->rkm", p, p,
                           preferred_element_type=jnp.float32)
        if val is None:
            rhs = jnp.sum(p, axis=1, dtype=jnp.float32)
        else:
            rhs = jnp.einsum("rck,rc->rk", p, val.astype(cd),
                             preferred_element_type=jnp.float32)
    return grams, rhs


def _gather_model_partial(y_local, col, compute_dtype):
    """ALX sharded gather: slots this shard owns, zero rows elsewhere.

    ``y_local`` is this device's slot shard of the counterpart factor
    matrix ([total_slots / m, k], MODEL_AXIS-sharded, contiguous blocks in
    axis order). Slot indices outside this shard's window — including the
    sentinel padding index — gather exact zeros, so psumming any
    per-entry-linear reduction of the result over MODEL_AXIS equals the
    full-gather reduction without ever materializing the full matrix on
    one device (PAPERS.md ALX, arxiv 2112.02194 §3).
    """
    cd = compute_dtype
    rows_local = y_local.shape[0]
    off = jax.lax.axis_index(MODEL_AXIS) * rows_local
    lc = col - off
    valid = (lc >= 0) & (lc < rows_local)
    p = jnp.take(y_local, jnp.clip(lc, 0, rows_local - 1), axis=0)
    return p.astype(cd) * valid[..., None].astype(cd)


def _slab_normal_eq(gather, colb, valb, *, sentinel, entries_per_step,
                    implicit, alpha, compute_dtype):
    """grams/rhs for one bucket slab [R, C], chunked over rows so the
    gathered [chunk, C, k] intermediate stays bounded."""
    R, C = colb.shape
    chunk_r = max(1, min(R, entries_per_step // max(C, 1)))
    n_sub = -(-R // chunk_r)
    kw = dict(implicit=implicit, alpha=alpha, compute_dtype=compute_dtype)
    if n_sub <= 1:
        return _grams_rows(gather(colb), valb, **kw)
    padR = n_sub * chunk_r - R
    cc = jnp.pad(colb, ((0, padR), (0, 0)), constant_values=sentinel)
    cc = cc.reshape(n_sub, chunk_r, C)

    if valb is None:  # binary-ratings: no value slab exists
        grams, rhs = jax.lax.map(
            lambda ccol: _grams_rows(gather(ccol), None, **kw), cc)
    else:
        vv = jnp.pad(valb, ((0, padR), (0, 0)))
        vv = vv.reshape(n_sub, chunk_r, C)

        def body(chunk):
            ccol, cval = chunk
            return _grams_rows(gather(ccol), cval, **kw)

        grams, rhs = jax.lax.map(body, (cc, vv))
    k = grams.shape[-1]
    return (grams.reshape(n_sub * chunk_r, k, k)[:R],
            rhs.reshape(n_sub * chunk_r, k)[:R])


def _ridge_solve(a, b, lam, yty, *, implicit, model_sharded, platform, k):
    """psum → +YᵀY → ridge → batched SPD solve (shared by the fused
    per-chunk path and the heavy-bucket path)."""
    if model_sharded:
        # Reconstruct the full per-row normal equations from the shard
        # partials — the one collective of the sharded gather.
        a = jax.lax.psum(a, MODEL_AXIS)
        b = jax.lax.psum(b, MODEL_AXIS)
    if implicit:
        a = a + yty[None, :, :]  # shared YᵀY term (all items)
    a = a + lam[:, None, None] * jnp.eye(k, dtype=jnp.float32)
    # Pallas VMEM Gauss-Jordan on TPU, XLA Cholesky elsewhere. platform
    # is the MESH's device platform, threaded from the caller —
    # jax.default_backend() is wrong here: the driver dry-runs a CPU mesh
    # while a TPU stays the process default backend (and vice versa in
    # tests), and pallas_call on CPU without interpret mode is an error.
    x = batched_spd_solve(a, b, vma=(DATA_AXIS,), platform=platform)
    return x.astype(jnp.float32)


#: rows per fused gather→gram→solve step: the Pallas solve's native slab
#: width, so per-chunk solves carry zero batch padding
_FUSED_CHUNK_ROWS = 512
#: cap on the gathered [chunk, C, k] slab bytes per fused step
_FUSED_SLAB_BYTES = 512 * 1024 * 1024


def _fused_bucket_solve(gather, colb, valb, lam_b, yty, *, sentinel,
                        entries_budget, implicit, alpha, compute_dtype,
                        model_sharded, platform, k):
    """One NON-overflow bucket: gather → per-row grams → ridge → solve,
    chunked over rows, never materializing the bucket's [R, k, k] normal
    equations (at rank 128 the full-side materialization would be ~11 GB
    at ML-20M — the r3 fused design keeps live memory per step at the
    [chunk, C, k] gather slab plus one [chunk, k, k] gram block).
    ``entries_budget``: user-configured cap on chunk_r × C (chunkTiles ×
    blockLen) — None = auto (512 rows, byte-capped)."""
    R, C = colb.shape
    cd_bytes = 2 if compute_dtype == jnp.bfloat16 else 4
    chunk_r = _FUSED_CHUNK_ROWS
    while chunk_r > 64 and chunk_r * C * k * cd_bytes > _FUSED_SLAB_BYTES:
        chunk_r //= 2
    if entries_budget is not None:
        chunk_r = max(1, min(chunk_r, entries_budget // max(C, 1) or 1))
    chunk_r = min(chunk_r, max(R, 1))
    n_sub = -(-R // chunk_r)
    kw = dict(implicit=implicit, alpha=alpha, compute_dtype=compute_dtype)

    def solve_chunk(ccol, cval, clam):
        grams, rhs = _grams_rows(gather(ccol), cval, **kw)
        return _ridge_solve(grams, rhs, clam, yty, implicit=implicit,
                            model_sharded=model_sharded, platform=platform,
                            k=k)

    if n_sub <= 1:
        return solve_chunk(colb, valb, lam_b)
    padR = n_sub * chunk_r - R
    cc = jnp.pad(colb, ((0, padR), (0, 0)), constant_values=sentinel)
    # padded lam rows: benign 1.0 ridge keeps the padded systems SPD
    ll = jnp.pad(lam_b, (0, padR), constant_values=1.0)
    if valb is None:  # binary-ratings: no value slab exists
        x = jax.lax.map(
            lambda chunk: solve_chunk(chunk[0], None, chunk[1]),
            (cc.reshape(n_sub, chunk_r, C), ll.reshape(n_sub, chunk_r)),
        )
    else:
        vv = jnp.pad(valb, ((0, padR), (0, 0)))
        x = jax.lax.map(
            lambda chunk: solve_chunk(*chunk),
            (cc.reshape(n_sub, chunk_r, C), vv.reshape(n_sub, chunk_r, C),
             ll.reshape(n_sub, chunk_r)),
        )
    return x.reshape(n_sub * chunk_r, k)[:R]


def _half_step_local(y, lam, yty, *bucket_args, plan: LayoutPlan,
                     sentinel, implicit, alpha, compute_dtype,
                     entries_per_step, entries_budget, platform,
                     model_sharded, binary=False):
    """Solve one side's factors for one shard's slots (runs inside
    shard_map; all arrays are the local shard).

    Replicated mode (``model_sharded=False``): ``y`` is the full
    counterpart matrix plus a trailing all-zero sentinel row that padding
    slot indices resolve to.

    Model-sharded mode: ``y`` is this device's MODEL_AXIS slot shard; the
    gather is partial (zeros for non-owned slots) and the per-row normal
    equations are psummed over MODEL_AXIS before the solve — the ALX
    sharded layout, so factor HBM scales with 1/m.

    Non-overflow buckets run the FUSED gather→gram→ridge→solve pipeline
    (no [R, k, k] materialization); the dedicated heavy bucket (overflow
    parents, plan.has_heavy_bucket) materializes its small gram block so
    the virtual slabs can scatter-add into it before its solve.
    """
    k = y.shape[1]
    n_buckets = len(plan.lengths)
    has_heavy = plan.has_heavy_bucket
    n_fused = n_buckets - (1 if has_heavy else 0)

    def gather(cols):
        # col slabs may arrive uint16 (narrow counterpart slot space —
        # half the upload bytes); widen per chunk, in-register.
        cols = cols.astype(jnp.int32)
        if model_sharded:
            return _gather_model_partial(y, cols, compute_dtype)
        return jnp.take(y, cols, axis=0).astype(compute_dtype)

    solve_kw = dict(implicit=implicit, model_sharded=model_sharded,
                    platform=platform, k=k)
    # binary mode: value slabs were never uploaded — every real entry is
    # 1.0, and padding/non-owned slots already gather zero factor ROWS,
    # so the per-entry weights collapse to scalars inside _grams_rows
    # (valb=None; no ones array is ever materialized).
    stride = 1 if binary else 2
    base = 0
    x_parts = []
    for bi in range(n_fused):
        colb = bucket_args[stride * bi]
        valb = None if binary else bucket_args[stride * bi + 1]
        R_b = colb.shape[0]
        x_parts.append(_fused_bucket_solve(
            gather, colb, valb, jax.lax.slice(lam, (base,), (base + R_b,)),
            yty, sentinel=sentinel, entries_budget=entries_budget,
            alpha=alpha, compute_dtype=compute_dtype, **solve_kw))
        base += R_b

    if has_heavy:
        colb = bucket_args[stride * n_fused]
        valb = None if binary else bucket_args[stride * n_fused + 1]
        if binary:
            v_cols, v_parent = bucket_args[n_buckets:n_buckets + 2]
            v_vals = None
        else:
            v_cols, v_vals, v_parent = (
                bucket_args[2 * n_buckets:2 * n_buckets + 3])
        R_h = colb.shape[0]
        kw = dict(sentinel=sentinel, entries_per_step=entries_per_step,
                  implicit=implicit, alpha=alpha,
                  compute_dtype=compute_dtype)
        a, b = _slab_normal_eq(gather, colb, valb, **kw)
        vg, vr = _slab_normal_eq(gather, v_cols, v_vals, **kw)
        # Merge overflow chunks into their parent rows; parents all live
        # in this (last) bucket, so re-base the shard-local slots.
        vp = v_parent - base
        a = a.at[vp].add(vg)
        b = b.at[vp].add(vr)
        x_parts.append(_ridge_solve(
            a, b, jax.lax.slice(lam, (base,), (base + R_h,)), yty,
            **solve_kw))

    return (jnp.concatenate(x_parts, axis=0) if len(x_parts) > 1
            else x_parts[0])


def _host_lam(plan: LayoutPlan, params: ALSParams) -> np.ndarray:
    """Per-slot ridge weights (static — computed once on the host)."""
    counts = plan.counts_slot.astype(np.float32)
    if params.lambda_scaling == "nratings":
        lam = params.reg * np.maximum(counts, 1.0)
    else:
        lam = np.full(counts.shape, params.reg, dtype=np.float32)
    # Slots with no ratings keep a well-conditioned system (solution 0).
    return (lam + np.where(counts == 0, 1e-6, 0.0)).astype(np.float32)


def _side_flat(arrs: BucketArrays, plan: LayoutPlan, lam: np.ndarray,
               binary: bool = False, col_sentinel: int | None = None):
    """Flatten one side's device args: per-bucket (col, val) pairs,
    optional (v_cols, v_vals, v_parent), then lam. ``binary``: value
    slabs are elided entirely (synthesized on device as ones).
    ``col_sentinel``: the counterpart sentinel index — when it fits
    uint16, col slabs upload at half width (the device widens per chunk
    inside the gather)."""
    narrow = col_sentinel is not None and col_sentinel <= np.iinfo(np.uint16).max

    def col(c):
        return c.astype(np.uint16) if narrow else c

    if binary:
        flat = [col(c) for c in arrs.cols]
        if plan.v_rows_per_shard > 0:
            flat += [col(arrs.v_cols), np.asarray(plan.v_parent, np.int32)]
    else:
        flat = []
        for c, v in zip(arrs.cols, arrs.vals):
            flat += [col(c), v]
        if plan.v_rows_per_shard > 0:
            flat += [col(arrs.v_cols), arrs.v_vals,
                     np.asarray(plan.v_parent, np.int32)]
    flat.append(lam)
    return flat


def _make_train_fn(mesh: Mesh, params: ALSParams, plan_u: LayoutPlan,
                   plan_i: LayoutPlan):
    """Build the jitted full training loop for fixed layouts. Returns
    (fitted_fn, in_shardings); call as fn(n_iters, x0, y0, *u_flat,
    *i_flat) with the _side_flat arg order."""
    params, entries_per_step = _resolve_params(mesh, params)
    # an EXPLICIT chunkTiles bounds the fused pipeline's slab too; auto
    # lets it target the solve's native 512-row chunks
    entries_budget = entries_per_step if params.chunk_tiles > 0 else None
    cd = jnp.bfloat16 if params.compute_dtype == "bfloat16" else jnp.float32
    implicit = params.implicit_prefs
    # Kernel selection must follow the MESH's platform, not the process
    # default backend (see _half_step_local docstring).
    mesh_platform = mesh.devices.flat[0].platform
    # 2-D (d, m) mesh → ALX factor sharding: the counterpart factor
    # matrix is row-sharded over MODEL_AXIS (HBM per device ∝ 1/m) and
    # the per-row normal equations are psummed from shard partials.
    model_sharded = MODEL_AXIS in mesh.axis_names

    row2 = P(DATA_AXIS, None)
    row1 = P(DATA_AXIS)
    rep = P()
    y_spec = P(MODEL_AXIS, None) if model_sharded else rep

    binary = bool(params.binary_ratings)

    def side_specs(plan: LayoutPlan):
        specs = []
        for _ in plan.lengths:
            specs += [row2] if binary else [row2, row2]
        if plan.v_rows_per_shard > 0:
            specs += ([row2, row1] if binary else [row2, row2, row1])
        specs.append(row1)  # lam
        return specs

    u_specs, i_specs = side_specs(plan_u), side_specs(plan_i)
    n_u_args = len(u_specs)

    def one_side(y, flat, plan, specs, sentinel):
        if model_sharded:
            # No sentinel row: the sharded gather masks by ownership
            # window (the sentinel index falls outside every window).
            y_cd = jax.lax.with_sharding_constraint(
                y.astype(cd), NamedSharding(mesh, y_spec))
        else:
            # Sentinel zero row appended so padding slot indices gather
            # 0s (mask-free hot loop); cast once so the hot loop gathers
            # half-width bf16 rows instead of f32.
            y_cd = jnp.concatenate(
                [y, jnp.zeros((1, y.shape[1]), y.dtype)], axis=0
            ).astype(cd)
        yty = (
            jnp.einsum("nk,nm->km", y_cd, y_cd,
                       preferred_element_type=jnp.float32)
            if implicit
            else jnp.zeros((params.rank, params.rank), jnp.float32)
        )
        lam = flat[-1]
        bucket_args = flat[:-1]
        fn = shard_map(
            functools.partial(
                _half_step_local,
                plan=plan,
                sentinel=sentinel,
                implicit=implicit,
                alpha=params.alpha,
                compute_dtype=cd,
                entries_per_step=entries_per_step,
                entries_budget=entries_budget,
                platform=mesh_platform,
                model_sharded=model_sharded,
                binary=binary,
            ),
            mesh=mesh,
            in_specs=(y_spec, row1, rep) + tuple(specs[:-1]),
            out_specs=row1,
        )
        x = fn(y_cd, lam, yty, *bucket_args)
        if model_sharded:
            # Solved slots leave the shard_map split over 'd'; re-shard to
            # the MODEL_AXIS storage layout (XLA all-to-all over ICI) so
            # the next half-step consumes it as a sharded counterpart.
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, y_spec))
        return x

    sent_u, sent_i = plan_u.total_slots, plan_i.total_slots

    # The big slab arrays enter as jit args (not baked-in constants), and
    # n_iters is traced so one compilation serves full runs, checkpoint
    # chunks, and resume remainders alike.
    def loop(n_iters, x0, y0, *flat):
        u_flat = flat[:n_u_args]
        i_flat = flat[n_u_args:]

        def body(_, carry):
            x, y = carry
            x = one_side(y, u_flat, plan_u, u_specs, sent_i)
            y = one_side(x, i_flat, plan_i, i_specs, sent_u)
            return (x, y)

        return jax.lax.fori_loop(0, n_iters, body, (x0, y0))

    factors_s = NamedSharding(mesh, y_spec)
    in_shardings = (
        NamedSharding(mesh, rep), factors_s, factors_s,
    ) + tuple(NamedSharding(mesh, s) for s in u_specs + i_specs)
    # Outputs stay MODEL_AXIS-sharded on a 2-D mesh — replicating here
    # would all-gather both full factor matrices onto every device and
    # defeat the 1/m HBM scaling (host device_get assembles from shards).
    # Multi-controller runs need replicated outputs so every process can
    # device_get its result.
    out_s = (factors_s if jax.process_count() == 1
             else NamedSharding(mesh, rep))
    fitted = jax.jit(
        loop,
        in_shardings=in_shardings,
        out_shardings=(out_s, out_s),
    )
    return fitted, in_shardings


def _mesh_dims(mesh: Mesh) -> tuple[int, int]:
    if DATA_AXIS not in mesh.axis_names:
        raise ValueError(f"mesh must have a '{DATA_AXIS}' axis, "
                         f"got {mesh.axis_names}")
    return mesh.shape[DATA_AXIS], mesh.shape.get(MODEL_AXIS, 1)


def _plan_signature(plan: LayoutPlan) -> tuple:
    """Everything _make_train_fn bakes into the executable for one side."""
    return (
        tuple(int(x) for x in plan.lengths),
        tuple(int(x) for x in plan.bucket_rows),
        plan.rows_per_shard, plan.n_shards, plan.v_rows_per_shard,
        plan.overflow_len, plan.total_slots,
    )


_train_fn_cache: dict = {}


def _cached_train_fn(mesh: Mesh, params: ALSParams, plan_u: LayoutPlan,
                     plan_i: LayoutPlan):
    """Reuse the jitted loop across train calls with identical mesh /
    params / layout shapes: jax's jit cache keys on the CALLABLE, so a
    fresh _make_train_fn closure per `pio train` would recompile the
    whole program (~3-6s) even for back-to-back trains on the same data
    shapes (repeat trains, eval sweeps, serving reload-retrain loops)."""
    key = (
        tuple(id(d) for d in mesh.devices.flat), mesh.axis_names,
        _executable_params_key(params),
        _plan_signature(plan_u), _plan_signature(plan_i),
        jax.process_count(),
    )
    hit = _train_fn_cache.get(key)
    if hit is None:
        hit = _make_train_fn(mesh, params, plan_u, plan_i)
        if len(_train_fn_cache) > 8:  # bound: old layouts just recompile
            _train_fn_cache.clear()
        _train_fn_cache[key] = hit
    return hit


def _pack_flat(flat):
    """Concatenate the per-bucket slabs into ONE 1-D buffer per dtype.

    Through the remote-PJRT tunnel every distinct transfer pays a fixed
    setup cost that the tunnel RE-PAYS after each big executable runs
    (measured on the tunneled v5e: the 69-slab Similar-Product upload
    costs ~1.2 s warm as individual puts vs ~35 ms packed).  Packing
    trades the per-slab transfers for 2-3 large ones plus free static
    slices inside the jitted loop.  Single-device meshes only — packing
    would destroy the per-slab DATA_AXIS shardings a real multi-chip
    mesh needs, and host-attached chips don't pay the tunnel tax."""
    groups: dict[str, list] = {}
    offsets: dict[str, int] = {}
    spec = []
    for a in flat:
        a = np.ascontiguousarray(a)
        ds = a.dtype.str
        off = offsets.get(ds, 0)
        spec.append((ds, off, a.shape))
        groups.setdefault(ds, []).append(a.ravel())
        offsets[ds] = off + a.size
    order = tuple(sorted(groups))
    bufs = tuple(
        groups[ds][0] if len(groups[ds]) == 1 else np.concatenate(groups[ds])
        for ds in order)
    return bufs, (order, tuple(spec))


_packed_fn_cache: dict = {}


#: ALSParams fields that do NOT shape the compiled program:
#: num_iterations is a traced operand, reg/lambda_scaling flow in as
#: the lam data array, seed only shapes the host init. Everything NOT
#: listed here keys the executable cache — a DENYLIST, so a future
#: field added to ALSParams fails safe (spurious recompile) instead of
#: silently serving a stale program compiled for different params.
_NON_SHAPING_PARAMS = frozenset(
    {"num_iterations", "reg", "lambda_scaling", "seed"})


def _executable_params_key(params: ALSParams) -> tuple:
    """Cache key over the ALSParams fields BAKED into the compiled
    program. Lets an eval sweep over regularization / iterations /
    seeds (the `pio eval` candidate pattern) reuse ONE executable with
    zero recompiles; with the device slab cache, binary-ratings sweeps
    additionally re-upload only the small lam vector per candidate
    (explicit-value sweeps re-upload the f32 buffer that lam is packed
    with — value slabs and lam share a dtype group)."""
    return tuple(
        getattr(params, f.name) for f in dataclasses.fields(params)
        if f.name not in _NON_SHAPING_PARAMS)

#: Device-resident slab cache: repeat trains over IDENTICAL data skip
#: the host->device upload entirely — the `pio eval` pattern (N
#: parameter candidates x one prepared dataset) and long-lived
#: retrain-on-reload servers. Keyed by content hash, so any changed
#: byte misses; param-dependent slabs (lam) simply hash differently per
#: candidate and re-upload at their own (tiny) cost. Bounded LRU over
#: device bytes; PIO_ALS_DEVICE_CACHE=0 disables.
_dev_buf_cache: "dict[tuple, object]" = {}
_dev_buf_cache_order: list = []
_DEV_BUF_CACHE_BYTES = 256 * 1024 * 1024


def _cached_dev_put(buf: np.ndarray, dev) -> "jax.Array":
    from ..common import envknobs

    if not envknobs.env_flag("PIO_ALS_DEVICE_CACHE", True):
        return jax.device_put(buf, dev)
    import hashlib

    digest = hashlib.blake2b(buf, digest_size=16).digest()
    key = (digest, buf.dtype.str, buf.shape, getattr(dev, "id", id(dev)))
    hit = _dev_buf_cache.get(key)
    if hit is not None:
        # LRU, not FIFO: refresh recency so a hot model's slabs aren't
        # the first evicted just because they were uploaded first
        _dev_buf_cache_order.remove(key)
        _dev_buf_cache_order.append(key)
        return hit
    arr = jax.device_put(buf, dev)
    _dev_buf_cache[key] = arr
    _dev_buf_cache_order.append(key)
    total = sum(int(np.prod(k[2])) * np.dtype(k[1]).itemsize
                for k in _dev_buf_cache)
    while total > _DEV_BUF_CACHE_BYTES and len(_dev_buf_cache_order) > 1:
        old = _dev_buf_cache_order.pop(0)
        victim = _dev_buf_cache.pop(old, None)
        if victim is not None:
            total -= int(np.prod(old[2])) * np.dtype(old[1]).itemsize
    return arr


def _cached_packed_train_fn(mesh: Mesh, params: ALSParams,
                            plan_u: LayoutPlan, plan_i: LayoutPlan,
                            pack_key):
    """jit(unpack-then-loop), cached like _cached_train_fn (the inner
    fn inlines — one executable, no double compile)."""
    key = (
        tuple(id(d) for d in mesh.devices.flat), mesh.axis_names,
        _executable_params_key(params),
        _plan_signature(plan_u), _plan_signature(plan_i),
        pack_key,
    )
    hit = _packed_fn_cache.get(key)
    if hit is None:
        fn, _ = _cached_train_fn(mesh, params, plan_u, plan_i)
        order, spec = pack_key
        buf_idx = {ds: k for k, ds in enumerate(order)}

        def packed(n_iters, x0, y0, *bufs):
            flat = []
            for ds, off, shape in spec:
                size = 1
                for dim in shape:
                    size *= dim
                flat.append(bufs[buf_idx[ds]][off:off + size].reshape(shape))
            return fn(n_iters, x0, y0, *flat)

        hit = jax.jit(packed)
        if len(_packed_fn_cache) > 8:
            _packed_fn_cache.clear()
        _packed_fn_cache[key] = hit
    return hit


def _fresh_init(params: ALSParams, plan_u: LayoutPlan, plan_i: LayoutPlan,
                n_users: int, n_items: int):
    """MLlib-style init (scaled standard normal), drawn in GLOBAL row
    order and placed into layout slots — identical factors regardless of
    mesh shape or layout, and filler slots start at exactly 0 (so the
    implicit-mode YᵀY term never sees garbage rows)."""
    k = params.rank
    rng = np.random.default_rng(params.seed)
    x0 = np.zeros((plan_u.total_slots, k), np.float32)
    y0 = np.zeros((plan_i.total_slots, k), np.float32)
    x0[plan_u.slot_of_row] = (
        rng.standard_normal((n_users, k)) / np.sqrt(k)).astype(np.float32)
    y0[plan_i.slot_of_row] = (
        rng.standard_normal((n_items, k)) / np.sqrt(k)).astype(np.float32)
    return x0, y0


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Optional[Mesh] = None,
    checkpoint_hook=None,
    resume: bool = False,
    timings: Optional[dict] = None,
    nan_guard: bool = False,
    nan_guard_stage: str = "algorithm[als]",
    pipeline=None,
) -> ALSFactors:
    """Train explicit/implicit ALS from a COO rating triple.

    ``nan_guard``: dispatch one iteration at a time and fail with
    "stage: algorithm[als], iteration k" on the first non-finite factor
    (SURVEY.md §5.2 sanitizer tier) instead of returning a garbage
    model. Trades the fused n-iteration executable's speed for
    attribution, exactly like jax_debug_nans' op-by-op replay.

    ``checkpoint_hook`` (workflow.checkpoint.CheckpointHook): when enabled,
    the loop runs in hook.every_n-iteration chunks through the SAME jitted
    executable (n_iters is traced — zero recompiles) and snapshots the
    factor pytree at each chunk boundary; ``resume=True`` restores the
    latest snapshot and runs only the remaining iterations. Chunking is
    bitwise-identical math to the single fori_loop. The reference cannot do
    this at all — a failed Spark ALS job restarts from zero (SURVEY.md §5.4).

    ``timings``: pass a dict to get the bench-grade phase breakdown
    (upload / compile / steady-state device seconds, with the scalar-
    readback completion barrier that the remote-PJRT tunnel requires —
    block_until_ready can return early through it). This is how bench.py
    measures the REAL product path: `pio train` → Engine.train →
    ALSAlgorithm → here. Single-process, non-checkpoint-chunked runs only.
    """
    mesh = mesh or default_mesh()
    d_size, m_size = _mesh_dims(mesh)

    if params.binary_ratings is None:
        params = dataclasses.replace(
            params,
            binary_ratings=bool(np.all(np.asarray(rating) == 1.0)))

    # Both sides' layout prep overlapped on input-pipeline worker
    # threads (rowblocks.plan_and_fill_both) — the host scatters are the
    # serial front of every ALS train and their GIL-releasing cores run
    # genuinely concurrent. ``pipeline`` (workflow ctx config, else env)
    # turns the overlap off with the rest of the streaming layer.
    if pipeline is None:
        from ..workflow.input_pipeline import PipelineConfig

        pipeline = PipelineConfig.from_env()
    plan_u, plan_i, arrs_u, arrs_i = plan_and_fill_both(
        user_idx, item_idx, rating, n_users, n_items, d_size,
        m_div=m_size, fill_vals=not params.binary_ratings,
        parallel=pipeline.mode != "off")

    k = params.rank
    x_shape = (plan_u.total_slots, k)
    y_shape = (plan_i.total_slots, k)

    # Fingerprint of the exact COO triple: resume is only sound against the
    # identical rating data (shape equality alone misses in-place rating
    # updates that keep n_users/n_items fixed). Only computed when a hook
    # is active — it's O(nnz) hashing that plain trains shouldn't pay.
    fingerprint = None
    if checkpoint_hook is not None:
        import zlib

        # Seeded with _LAYOUT_TAG (layout generation) and the slot
        # permutations (mesh-dependent): factors are stored in slot
        # order, so a snapshot is only resumable by a run with the
        # IDENTICAL plan — same data AND same (d, m) mesh shape.
        layout_fp = zlib.crc32(
            plan_i.slot_of_row.tobytes(),
            zlib.crc32(plan_u.slot_of_row.tobytes(), _LAYOUT_TAG))
        fingerprint = zlib.crc32(
            np.asarray(rating, np.float32).tobytes(),
            zlib.crc32(np.asarray(item_idx).tobytes(),
                       zlib.crc32(np.asarray(user_idx).tobytes(),
                                  layout_fp)))

    start_iter = 0
    x0 = y0 = None
    if checkpoint_hook is not None and resume:
        from ..workflow.checkpoint import CheckpointIncompatibleError

        step = checkpoint_hook.latest_step()
        if step is not None and step < params.num_iterations:
            start_iter, tree = checkpoint_hook.restore(step)
            rx, ry = np.asarray(tree["user_factors"]), np.asarray(tree["item_factors"])
            if rx.shape != x_shape or ry.shape != y_shape:
                raise CheckpointIncompatibleError(
                    f"checkpoint shapes {rx.shape}/{ry.shape} do not match the "
                    f"current data layout {x_shape}/{y_shape}; the event data "
                    "changed since the interrupted run — retrain from scratch"
                )
            saved_fp = int(np.asarray(tree.get("fingerprint", -1)))
            if saved_fp != fingerprint:
                raise CheckpointIncompatibleError(
                    "checkpoint was written against different rating data "
                    "(fingerprint mismatch); the event store changed since "
                    "the interrupted run — retrain from scratch"
                )
            x0, y0 = rx, ry
        elif step is not None:
            # Snapshots are never written at the final iteration, so a
            # checkpoint at step >= num_iterations means the params changed
            # (num_iterations lowered) since the interrupted run.
            raise CheckpointIncompatibleError(
                f"latest checkpoint is at iteration {step} but only "
                f"{params.num_iterations} iterations were requested; the "
                "snapshot is from a run with more iterations — retrain from "
                "scratch or raise num_iterations"
            )

    if x0 is None:
        x0, y0 = _fresh_init(params, plan_u, plan_i, n_users, n_items)
    fn, in_shardings = _cached_train_fn(mesh, params, plan_u, plan_i)
    binary = bool(params.binary_ratings)
    flat = tuple(
        _side_flat(arrs_u, plan_u, _host_lam(plan_u, params), binary,
                   col_sentinel=plan_i.total_slots)
        + _side_flat(arrs_i, plan_i, _host_lam(plan_i, params), binary,
                     col_sentinel=plan_u.total_slots))
    if jax.process_count() > 1:
        # Multi-controller: every process holds the SAME full numpy
        # arrays (the event store is shared), so build global jax.Arrays
        # explicitly — jit refuses sharded numpy inputs across processes.
        def _globalize(host, sharding):
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx]
            )

        x0 = _globalize(np.asarray(x0), in_shardings[1])
        y0 = _globalize(np.asarray(y0), in_shardings[2])
        flat = tuple(
            _globalize(np.asarray(b), s)
            for b, s in zip(flat, in_shardings[3:])
        )
    chunk = checkpoint_hook.every_n if checkpoint_hook is not None and checkpoint_hook.enabled else 0
    timed_path = (not nan_guard
                  and timings is not None and jax.process_count() == 1
                  and not (chunk and params.num_iterations - start_iter > chunk))
    # Single-device runs pack the slabs: 2-3 large transfers instead of
    # ~70 small ones (see _pack_flat — the remote tunnel re-pays a
    # per-transfer setup cost after every executable run, which made the
    # upload, not the device math, dominate the warm Similar-Product
    # train).  run_fn/run_args abstract over packed vs per-slab.
    packed = jax.process_count() == 1 and mesh.devices.size == 1
    if packed:
        bufs, pack_key = _pack_flat(flat)
        run_fn = _cached_packed_train_fn(mesh, params, plan_u, plan_i,
                                         pack_key)
        run_args = bufs
        dev = mesh.devices.flat[0]
        put_args = lambda: tuple(_cached_dev_put(b, dev) for b in run_args)  # noqa: E731
    else:
        run_fn = fn
        run_args = flat
        put_args = lambda: tuple(  # noqa: E731
            fast_put(np.asarray(b), sh)
            for b, sh in zip(run_args, in_shardings[3:]))
    if jax.process_count() == 1 and not timed_path:
        # Explicit transfers: handing jit raw numpy inputs routes them
        # through the sharded-copy machinery, ~30x slower than plain
        # single-device puts through the remote-PJRT tunnel.  The timed
        # branch below does its own (timed) puts instead.
        x0 = fast_put(np.asarray(x0), in_shardings[1])
        y0 = fast_put(np.asarray(y0), in_shardings[2])
        run_args = put_args()
    if timed_path:
        import time as _time

        t0 = _time.perf_counter()
        dx0 = fast_put(np.asarray(x0), in_shardings[1])
        dy0 = fast_put(np.asarray(y0), in_shardings[2])
        dev_args = put_args()
        jax.block_until_ready((dx0, dy0, dev_args))
        timings["upload_seconds"] = _time.perf_counter() - t0

        n = np.int32(params.num_iterations - start_iter)
        t0 = _time.perf_counter()
        compiled = run_fn.lower(n, dx0, dy0, *dev_args).compile()
        timings["compile_seconds"] = _time.perf_counter() - t0

        # Warm-up dispatch (n_iters is traced: same executable, zero work),
        # then the timed run with a scalar readback as the completion
        # barrier — through the remote-PJRT tunnel block_until_ready can
        # return before the device finishes, a device_get cannot.
        warm = compiled(np.int32(0), dx0, dy0, *dev_args)
        _ = jax.device_get(warm[0][:1, :1])
        t0 = _time.perf_counter()
        x, y = compiled(n, dx0, dy0, *dev_args)
        _ = jax.device_get(x[:1, :1])
        timings["device_train_seconds"] = _time.perf_counter() - t0
    elif nan_guard:
        # Sanitizer tier: one dispatch per iteration + a device-side
        # finite reduction (ONE scalar fetched per iteration — pulling
        # the full factor matrices would be transfer-bound through the
        # remote tunnel), so the failure names the iteration that
        # produced it. Checkpoint saves keep their chunk schedule.
        from ..common.nan_guard import NaNGuardError

        finite_probe = jax.jit(
            lambda a, b: jnp.isfinite(a).all() & jnp.isfinite(b).all())
        x, y = x0, y0
        for it in range(start_iter, params.num_iterations):
            fault_point("train.sweep")
            x, y = run_fn(np.int32(1), x, y, *run_args)
            # Beat AFTER the dispatch: the first sweep includes the XLA
            # compile, and the supervisor's stall detector only arms at
            # the first beat (init grace covers everything before it).
            gang.beat()
            if not bool(jax.device_get(finite_probe(x, y))):
                raise NaNGuardError(
                    f"stage: {nan_guard_stage}, iteration {it + 1}: "
                    "non-finite factors (check input ratings for NaN/Inf "
                    "or raise the regularization)")
            done = it + 1
            saved = False
            if chunk and done % chunk == 0 and done < params.num_iterations:
                checkpoint_hook.save(
                    done, {"user_factors": x, "item_factors": y,
                           "fingerprint": np.int64(fingerprint)}
                )
                saved = True
                gang.beat()  # a save (manager init, fsync) can be slow too
            # Per-iteration dispatch ⇒ drain can honor EVERY sweep
            # boundary, not just the checkpoint cadence; an off-cadence
            # drain writes its own snapshot (all processes agree:
            # `saved` is deterministic and the flag is allgathered).
            if done < params.num_iterations and gang.drain_requested_global():
                if chunk and not saved:
                    checkpoint_hook.save(
                        done, {"user_factors": x, "item_factors": y,
                               "fingerprint": np.int64(fingerprint)}
                    )
                raise gang.GangDrainRequested(done)
    elif chunk and params.num_iterations - start_iter > chunk:
        x, y = x0, y0
        it = start_iter
        while it < params.num_iterations:
            fault_point("train.sweep")
            n = min(chunk, params.num_iterations - it)
            x, y = run_fn(n, x, y, *run_args)
            gang.beat()  # after the dispatch: sweep 1 includes compile
            it += n
            if it < params.num_iterations:
                checkpoint_hook.save(
                    it, {"user_factors": x, "item_factors": y,
                         "fingerprint": np.int64(fingerprint)}
                )
                gang.beat()  # a save (manager init, fsync) can be slow too
                if gang.drain_requested_global():
                    raise gang.GangDrainRequested(it)
    else:
        x, y = run_fn(params.num_iterations - start_iter, x0, y0, *run_args)
        gang.beat()
    x, y = jax.device_get((x, y))
    return ALSFactors(
        user_factors=np.asarray(x)[plan_u.slot_of_row],
        item_factors=np.asarray(y)[plan_i.slot_of_row],
        n_users=n_users,
        n_items=n_items,
    )


def process_row_ranges(n_rows: int, mesh: Optional[Mesh] = None
                       ) -> tuple[int, int]:
    """[row0, row1) of entity rows THIS process owns on the mesh data axis.

    The contract for sharded multi-host ingest: each training process
    range-reads only the events whose solved-side row falls in its range
    (one range per side), instead of every host scanning the full store.
    Deterministic from (n_rows, mesh) alone — no coordination needed.
    Ranges are in LOGICAL row ids (the layout's internal slot padding
    never changes ownership); row1 may exceed n_rows on the last process.
    """
    mesh = mesh or default_mesh()
    d_size, _ = _mesh_dims(mesh)
    rpl = -(-n_rows // d_size)
    n_proc = jax.process_count()
    if d_size % n_proc:
        # Same contract train_als_process_sharded enforces; failing here
        # keeps callers from range-reading wrong slices before train raises.
        raise ValueError(
            f"data axis size {d_size} is not divisible by "
            f"{n_proc} processes")
    shards_per_proc = d_size // n_proc
    p = jax.process_index()
    return p * shards_per_proc * rpl, (p + 1) * shards_per_proc * rpl


def train_als_process_sharded(
    user_slice: tuple[np.ndarray, np.ndarray, np.ndarray],
    item_slice: tuple[np.ndarray, np.ndarray, np.ndarray],
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Optional[Mesh] = None,
    checkpoint_hook=None,
    resume: bool = False,
) -> ALSFactors:
    """Multi-controller ALS where each process ingests ONLY its shard.

    ``user_slice`` = (user_idx, item_idx, rating) holding exactly the
    events whose USER row this process owns (``process_row_ranges(
    n_users)``); ``item_slice`` = the same tuple order, holding the
    events whose ITEM row this process owns. In a deployment
    these are two range-reads against the shared event store — no host
    ever materializes the full dataset (the Spark-side analog is
    partitioned RDD ingest, SURVEY.md §2.10).

    The layout is a pure function of the per-row nnz counts, so ONE
    allgather of each side's local counts gives every process the
    identical global plan; each then fills only its own shards and the
    arrays are assembled with ``jax.make_array_from_process_local_data``.
    Factors match the single-process run bit-for-bit. Works on 1-D data
    meshes AND 2-D (d, m) ALX meshes — ownership windows are windows of
    layout slots, independent of which process filled them.

    ``checkpoint_hook``/``resume``: same contract as train_als; every
    process drives the (multihost-coordinated) orbax hook — the primary
    writes, the rest participate in its barriers. Factors are replicated
    across processes in multi-controller runs, so the snapshots are
    identical regardless of which process persists them.
    """
    mesh = mesh or default_mesh()
    d_size, m_size = _mesh_dims(mesh)
    n_proc = jax.process_count()
    if d_size % n_proc:
        raise ValueError(f"{d_size} devices do not divide {n_proc} processes")
    n_local = d_size // n_proc
    p = jax.process_index()
    shard0 = p * n_local

    from jax.experimental import multihost_utils

    def _global_counts(rows, n_rows):
        """Allgather per-process local counts into the full count vector
        (each process counts only rows it owns; ranges are disjoint)."""
        rpl = -(-n_rows // d_size)
        seg = n_local * rpl
        local = np.zeros(seg, np.int64)
        rows = np.asarray(rows, np.int64)
        row0 = p * seg
        if rows.size:
            if rows.min() < row0 or rows.max() >= row0 + seg:
                raise ValueError(
                    "sharded ingest: got rows outside this process's range "
                    f"[{row0}, {row0 + seg}) — the caller must range-read "
                    "only owned rows (process_row_ranges); got rows in "
                    f"[{rows.min()}, {rows.max()}] (n_rows={n_rows}, "
                    f"p={p}, n_local={n_local}, d={d_size})")
            local = np.bincount(rows - row0, minlength=seg)[:seg]
        gathered = np.asarray(
            multihost_utils.process_allgather(local)).reshape(-1)
        return gathered[:n_rows]

    # The ladder growth shapes the GLOBAL layout plan, so every process
    # must agree on it before planning — a silent cross-host env mismatch
    # would yield divergent plans whose shape-mismatched collectives hang
    # or corrupt. Allgather-verify like binary_ratings below.
    growth = ladder_growth()
    # Gather the float64 BIT PATTERN as two int32s: device_put silently
    # canonicalizes float64→float32 and int64→int32 (x64 mode is never
    # on), which would corrupt either wider representation; int32 is the
    # one dtype the gather leaves untouched (binary_ratings below relies
    # on the same fact).
    growth_bits = np.frombuffer(np.float64(growth).tobytes(), np.int32)
    all_growth = np.asarray(multihost_utils.process_allgather(
        growth_bits)).reshape(-1, 2)
    if not np.all(all_growth == growth_bits[None, :]):
        seen = sorted(set(
            float(np.frombuffer(np.asarray(row, np.int32).tobytes(),
                                np.float64)[0])
            for row in all_growth))
        raise ValueError(
            "PIO_ALS_LADDER_GROWTH disagrees across processes: "
            f"{seen} — every host must set the same value (it shapes "
            "the global factor layout)")

    # Both slices use (user_idx, item_idx, rating) tuple order; the
    # solved-side ROW array is user_slice[0] resp. item_slice[1].
    counts_u = _global_counts(user_slice[0], n_users)
    counts_i = _global_counts(item_slice[1], n_items)
    plan_u = plan_layout(counts_u, d_size, m_div=m_size)
    plan_i = plan_layout(counts_i, d_size, m_div=m_size)

    if params.binary_ratings is None:
        # Every process must pick the SAME jit signature: AND the local
        # all-ones verdicts (a process's slice can be all-ones while
        # another's is not).
        local_bin = np.array([
            np.all(np.asarray(user_slice[2]) == 1.0)
            and np.all(np.asarray(item_slice[2]) == 1.0)], np.int32)
        agreed = np.asarray(
            multihost_utils.process_allgather(local_bin)).all()
        params = dataclasses.replace(params, binary_ratings=bool(agreed))
    binary = bool(params.binary_ratings)

    arrs_u = fill_buckets(plan_u, user_slice[0], user_slice[1], user_slice[2],
                          col_slot_map=plan_i.slot_of_row,
                          sentinel=plan_i.total_slots,
                          shard0=shard0, n_local_shards=n_local,
                          fill_vals=not binary)
    arrs_i = fill_buckets(plan_i, item_slice[1], item_slice[0], item_slice[2],
                          col_slot_map=plan_u.slot_of_row,
                          sentinel=plan_u.total_slots,
                          shard0=shard0, n_local_shards=n_local,
                          fill_vals=not binary)

    fn, in_shardings = _cached_train_fn(mesh, params, plan_u, plan_i)
    flat_local = (
        _side_flat(arrs_u, plan_u, _host_lam(plan_u, params), binary,
                   col_sentinel=plan_i.total_slots)
        + _side_flat(arrs_i, plan_i, _host_lam(plan_i, params), binary,
                     col_sentinel=plan_u.total_slots))

    def _to_global(local, sharding):
        # Every per-side device arg is row-sharded over the data axis;
        # this process supplies its own shards' slice.
        local = np.asarray(local)
        global_rows = local.shape[0] * n_proc
        return jax.make_array_from_process_local_data(
            sharding, local, (global_rows,) + local.shape[1:])

    # lam and v_parent are global per-slot vectors in _side_flat; slice
    # them to this process's shards before assembly.
    def _slice_side(flat, plan):
        out = list(flat)
        rps = plan.rows_per_shard
        out[-1] = out[-1][shard0 * rps:(shard0 + n_local) * rps]
        if plan.v_rows_per_shard > 0:
            rv = plan.v_rows_per_shard
            out[-2] = out[-2][shard0 * rv:(shard0 + n_local) * rv]
        return out

    per_bucket = 1 if binary else 2
    n_u_args = (per_bucket * len(plan_u.lengths)
                + ((per_bucket + 1) if plan_u.v_rows_per_shard else 0) + 1)
    u_flat = _slice_side(flat_local[:n_u_args], plan_u)
    i_flat = _slice_side(flat_local[n_u_args:], plan_i)
    flat = tuple(
        _to_global(b, s)
        for b, s in zip(u_flat + i_flat, in_shardings[3:])
    )

    x0, y0 = _fresh_init(params, plan_u, plan_i, n_users, n_items)

    fingerprint = None
    if checkpoint_hook is not None:
        import zlib

        # Process-invariant fingerprint: every process sees only its own
        # slice, so hash the local slice and allgather the per-process
        # digests — combined in process order, the result is identical
        # everywhere (and still covers the full global triple).
        layout_fp = zlib.crc32(
            plan_i.slot_of_row.tobytes(),
            zlib.crc32(plan_u.slot_of_row.tobytes(), _LAYOUT_TAG))
        local_fp = zlib.crc32(
            np.asarray(user_slice[2], np.float32).tobytes(),
            zlib.crc32(np.asarray(user_slice[1], np.int64).tobytes(),
                       zlib.crc32(np.asarray(user_slice[0], np.int64)
                                  .tobytes(), layout_fp)))
        all_fp = np.asarray(multihost_utils.process_allgather(
            np.int64(local_fp))).reshape(-1)
        fingerprint = zlib.crc32(
            all_fp.tobytes(),
            zlib.crc32(np.asarray(counts_u).tobytes(),
                       zlib.crc32(np.asarray(counts_i).tobytes(),
                                  layout_fp)))

    start_iter = 0
    if checkpoint_hook is not None and resume:
        from ..workflow.checkpoint import CheckpointIncompatibleError

        step = checkpoint_hook.latest_step()
        if step is not None and step < params.num_iterations:
            start_iter, tree = checkpoint_hook.restore(step)
            rx = np.asarray(tree["user_factors"])
            ry = np.asarray(tree["item_factors"])
            if rx.shape != x0.shape or ry.shape != y0.shape or \
                    int(np.asarray(tree.get("fingerprint", -1))) != fingerprint:
                raise CheckpointIncompatibleError(
                    "checkpoint does not match the current sharded layout/"
                    "data — retrain from scratch")
            x0, y0 = rx, ry

    gx0 = jax.make_array_from_callback(
        x0.shape, in_shardings[1], lambda idx: x0[idx])
    gy0 = jax.make_array_from_callback(
        y0.shape, in_shardings[2], lambda idx: y0[idx])

    chunk = (checkpoint_hook.every_n
             if checkpoint_hook is not None and checkpoint_hook.enabled else 0)
    if chunk and params.num_iterations - start_iter > chunk:
        x, y = gx0, gy0
        it = start_iter
        while it < params.num_iterations:
            fault_point("train.sweep")
            n = min(chunk, params.num_iterations - it)
            x, y = fn(np.int32(n), x, y, *flat)
            gang.beat()  # after the dispatch: sweep 1 includes compile
            it += n
            if it < params.num_iterations:
                # EVERY process calls save: orbax's CheckpointManager is
                # multihost-coordinated (its own barriers; the primary
                # process writes, the rest participate). Factors are
                # replicated in multi-controller runs, so the pytrees
                # are identical across processes.
                checkpoint_hook.save(
                    it, {"user_factors": np.asarray(jax.device_get(x)),
                         "item_factors": np.asarray(jax.device_get(y)),
                         "fingerprint": np.int64(fingerprint)})
                gang.beat()  # a save (manager init, barriers) can be slow
                # Collective drain check (allgathered): every process
                # takes this branch at the SAME boundary or none does.
                if gang.drain_requested_global():
                    raise gang.GangDrainRequested(it)
    else:
        x, y = fn(np.int32(params.num_iterations - start_iter), gx0, gy0,
                  *flat)
        gang.beat()
    x, y = jax.device_get((x, y))
    return ALSFactors(
        user_factors=np.asarray(x)[plan_u.slot_of_row],
        item_factors=np.asarray(y)[plan_i.slot_of_row],
        n_users=n_users,
        n_items=n_items,
    )


#: Cap on one fused gather→gram chunk's [CH, k, k] f32 outer-product
#: slab in the partition-local trainer (the analog of _FUSED_SLAB_BYTES
#: for the event-COO layout).
_DP_CHUNK_BYTES = 64 * 1024 * 1024

#: Checkpoint-fingerprint seed of the partition-local (event-sharded)
#: layout — distinct from the slab layout's _LAYOUT_TAG so a snapshot
#: written by one trainer is rejected deterministically by the other
#: even when the factor shapes coincide.
_DP_LAYOUT_TAG = 0x70_10_10_01


def _dp_chunk(e_pad: int, k: int) -> int:
    """Events per fused gram chunk: bounded so the [CH, k, k] f32
    outer-product slab stays under _DP_CHUNK_BYTES."""
    ch = max(512, _DP_CHUNK_BYTES // max(k * k * 4, 1))
    return min(ch, max(e_pad, 1))


def _make_dp_train_fn(mesh: Mesh, params: ALSParams, n_u_pad: int,
                      n_i_pad: int, e_pad: int):
    """Build the jitted partition-local (data-parallel) ALS loop.

    Layout: the EVENT COO is sharded over the data axis (each gang
    worker supplies only its partitions' events — arbitrary rows, any
    order); factor matrices are replicated. One half-step computes
    per-row normal-equation partials from the local events
    (segment-sum of per-entry outer products — :func:`_grams_rows`
    linearity is exactly why partition-partial grams are sound), then
    **all-reduces the grams/rhs over the mesh** (the ALX replicated-
    grams recipe, arxiv 2112.02194), solves each device's own factor
    ROW BLOCK, and all-gathers the solved blocks back to a replicated
    factor matrix. The only collectives are the gram psum and the
    factor all-gather — no raw events ever cross the mesh. HBM bound:
    O(n_rows·k²) for the replicated normal equations per device; the
    slab trainer (:func:`train_als`) remains the path for models past
    that bound.
    """
    params, _ = _resolve_params(mesh, params)
    cd = jnp.bfloat16 if params.compute_dtype == "bfloat16" else jnp.float32
    implicit = params.implicit_prefs
    alpha = params.alpha
    nratings = params.lambda_scaling == "nratings"
    mesh_platform = mesh.devices.flat[0].platform
    if MODEL_AXIS in mesh.axis_names:
        raise ValueError(
            "the partition-local feed trainer shards factor blocks over "
            "the data axis only; 2-D (d, m) ALX meshes need the slab "
            "trainer (train_als / train_als_process_sharded)")
    d_size = mesh.shape[DATA_AXIS]
    k = params.rank
    rps_u = n_u_pad // d_size
    rps_i = n_i_pad // d_size
    ch = _dp_chunk(e_pad, k)
    assert e_pad % ch == 0, (e_pad, ch)
    n_ch = e_pad // ch
    eye = np.eye(k, dtype=np.float32)

    def lam_of(counts, reg):
        lam = (reg * jnp.maximum(counts, 1.0) if nratings
               else jnp.full(counts.shape, reg, jnp.float32))
        return lam + jnp.where(counts == 0, 1e-6, 0.0)

    def local_loop(n_iters, reg, x0, y0, u_loc, i_loc, r_loc, w_loc):
        # per-row GLOBAL observation counts (for nratings λ and the
        # zero-row conditioning), one psum each, computed once
        cnt_u = jax.lax.psum(
            jax.ops.segment_sum(w_loc, u_loc, num_segments=n_u_pad),
            DATA_AXIS)
        cnt_i = jax.lax.psum(
            jax.ops.segment_sum(w_loc, i_loc, num_segments=n_i_pad),
            DATA_AXIS)
        lam_u, lam_i = lam_of(cnt_u, reg), lam_of(cnt_i, reg)

        def half(y, rows, cols, lam, rps, n_pad):
            y_cd = y.astype(cd)
            yty = (jnp.einsum("nk,nm->km", y_cd, y_cd,
                              preferred_element_type=jnp.float32)
                   if implicit
                   else jnp.zeros((k, k), jnp.float32))
            if implicit:
                # Hu-Koren-Volinsky per-entry weights (same algebra as
                # _grams_rows' explicit-value implicit mode)
                gw = alpha * r_loc * w_loc
                bw = (1.0 + alpha * r_loc) * w_loc
            else:
                gw = w_loc
                bw = r_loc * w_loc

            def chunk(c, acc):
                g_acc, b_acc = acc
                sl = lambda a: jax.lax.dynamic_slice_in_dim(  # noqa: E731
                    a, c * ch, ch)
                cc, rr = sl(cols), sl(rows)
                p = jnp.take(y_cd, cc, axis=0)          # [CH, k]
                outer = jnp.einsum(
                    "ek,em->ekm", p * sl(gw)[:, None].astype(cd), p,
                    preferred_element_type=jnp.float32)
                rhs = jnp.einsum(
                    "ek,e->ek", p, sl(bw).astype(cd),
                    preferred_element_type=jnp.float32)
                return (g_acc + jax.ops.segment_sum(
                            outer, rr, num_segments=n_pad),
                        b_acc + jax.ops.segment_sum(
                            rhs, rr, num_segments=n_pad))

            g0 = jnp.zeros((n_pad, k, k), jnp.float32)
            b0 = jnp.zeros((n_pad, k), jnp.float32)
            grams, rhs = jax.lax.fori_loop(0, n_ch, chunk, (g0, b0))
            # replicated grams across the mesh (ALX): partition
            # partials sum to the full normal equations
            grams = jax.lax.psum(grams, DATA_AXIS)
            rhs = jax.lax.psum(rhs, DATA_AXIS)
            idx = jax.lax.axis_index(DATA_AXIS)
            a_blk = jax.lax.dynamic_slice_in_dim(grams, idx * rps, rps)
            b_blk = jax.lax.dynamic_slice_in_dim(rhs, idx * rps, rps)
            lam_blk = jax.lax.dynamic_slice_in_dim(lam, idx * rps, rps)
            if implicit:
                a_blk = a_blk + yty[None, :, :]
            a_blk = a_blk + lam_blk[:, None, None] * eye
            x_blk = batched_spd_solve(a_blk, b_blk, vma=(DATA_AXIS,),
                                      platform=mesh_platform)
            # factor blocks sharded over the data axis re-assemble to
            # the replicated matrix the next half-step gathers from
            return jax.lax.all_gather(
                x_blk.astype(jnp.float32), DATA_AXIS, axis=0,
                tiled=True)

        def body(_, carry):
            x, y = carry
            x = half(y, u_loc, i_loc, lam_u, rps_u, n_u_pad)
            y = half(x, i_loc, u_loc, lam_i, rps_i, n_i_pad)
            return (x, y)

        return jax.lax.fori_loop(0, n_iters, body, (x0, y0))

    rep = P()
    row1 = P(DATA_AXIS)
    fn = shard_map(
        local_loop, mesh=mesh,
        in_specs=(rep, rep, rep, rep, row1, row1, row1, row1),
        out_specs=(rep, rep))
    in_shardings = tuple(
        NamedSharding(mesh, s)
        for s in (rep, rep, rep, rep, row1, row1, row1, row1))
    fitted = jax.jit(fn, in_shardings=in_shardings,
                     out_shardings=(NamedSharding(mesh, rep),) * 2)
    return fitted, in_shardings


_dp_fn_cache: dict = {}


def _cached_dp_train_fn(mesh: Mesh, params: ALSParams, n_u_pad: int,
                        n_i_pad: int, e_pad: int):
    key = (
        tuple(id(d) for d in mesh.devices.flat), mesh.axis_names,
        # lambda_scaling is non-shaping for the SLAB trainer (λ arrives
        # as data) but the dp kernel computes λ in-graph from counts —
        # the branch is baked into the executable, so it must key it
        _executable_params_key(params), params.lambda_scaling,
        n_u_pad, n_i_pad, e_pad,
        jax.process_count(),
    )
    hit = _dp_fn_cache.get(key)
    if hit is None:
        hit = _make_dp_train_fn(mesh, params, n_u_pad, n_i_pad, e_pad)
        if len(_dp_fn_cache) > 8:
            _dp_fn_cache.clear()
        _dp_fn_cache[key] = hit
    return hit


def train_als_partition_local(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    params: ALSParams,
    mesh: Optional[Mesh] = None,
    checkpoint_hook=None,
    resume: bool = False,
    nan_guard: bool = False,
    nan_guard_stage: str = "algorithm[als]",
    force_dp: bool = False,
) -> ALSFactors:
    """ALS over PARTITION-LOCAL events: each gang process passes only
    the (user, item, rating) triple its event-log partitions hold —
    any rows, any order, already mapped to GLOBAL indices via the
    allgathered id vocabularies (workflow/train_feed.py). Unlike
    :func:`train_als_process_sharded` there is no row-ownership
    contract on the input: per-row normal equations are linear in
    per-event contributions, so partition partials all-reduce to the
    exact full-data equations (see :func:`_make_dp_train_fn`).

    Single-process calls fall back to :func:`train_als` (the data is
    complete locally, and the slab trainer is the faster single-host
    path); ``force_dp=True`` runs the data-parallel kernel anyway —
    the math-parity tests rely on it.

    ``checkpoint_hook``/``resume``/``nan_guard``: same contracts as
    the other trainers (chunked dispatch through one traced-n_iters
    executable, gang beats after every dispatch, allgathered drain at
    chunk boundaries, per-iteration finite probe under nan_guard).
    """
    mesh = mesh or default_mesh()
    if jax.process_count() == 1 and not force_dp:
        return train_als(user_idx, item_idx, rating, n_users, n_items,
                         params, mesh=mesh,
                         checkpoint_hook=checkpoint_hook, resume=resume,
                         nan_guard=nan_guard,
                         nan_guard_stage=nan_guard_stage)
    d_size, m_size = _mesh_dims(mesh)
    if m_size != 1:
        raise ValueError(
            "partition-local training needs a 1-D data mesh (factor "
            "blocks shard over 'd'); unset PIO_MESH_SHAPE's model axis")
    n_proc = jax.process_count()
    if d_size % n_proc:
        raise ValueError(
            f"data axis size {d_size} is not divisible by {n_proc} "
            "processes")
    n_local_devs = d_size // n_proc
    # The jit signature must agree across the gang: no per-process
    # auto-detection (a worker whose partitions happen to be all-ones
    # must not compile a different program than its peers).
    if params.binary_ratings is None:
        params = dataclasses.replace(params, binary_ratings=False)

    u = np.asarray(user_idx, np.int64)
    i = np.asarray(item_idx, np.int64)
    r = np.asarray(rating, np.float32)
    if u.size and (u.min() < 0 or u.max() >= n_users):
        raise ValueError("user_idx outside [0, n_users)")
    if i.size and (i.min() < 0 or i.max() >= n_items):
        raise ValueError("item_idx outside [0, n_items)")

    def roundup(n, m):
        return max(m, -(-n // m) * m)

    n_u_pad = roundup(n_users, d_size)
    n_i_pad = roundup(n_items, d_size)

    from jax.experimental import multihost_utils

    def agather(v):
        if n_proc == 1:
            return np.asarray([v])
        return np.asarray(
            multihost_utils.process_allgather(np.int32(v))).reshape(-1)

    # per-DEVICE event capacity: the max over the gang, so every shard
    # carries the same (padded) event count and the jit signature is
    # identical everywhere
    e_dev = int(agather(-(-max(u.size, 1) // n_local_devs)).max())
    ch = _dp_chunk(e_dev, params.rank)
    e_dev = roundup(e_dev, ch)
    e_local = e_dev * n_local_devs

    def pad_to(a, fill=0):
        out = np.full(e_local, fill, a.dtype)
        out[:a.size] = a
        return out

    u_loc = pad_to(u.astype(np.int32))
    i_loc = pad_to(i.astype(np.int32))
    r_loc = pad_to(r)
    w_loc = pad_to(np.ones(u.size, np.float32))

    fn, in_shardings = _cached_dp_train_fn(mesh, params, n_u_pad,
                                           n_i_pad, e_dev)

    k = params.rank
    rng = np.random.default_rng(params.seed)
    x0 = np.zeros((n_u_pad, k), np.float32)
    y0 = np.zeros((n_i_pad, k), np.float32)
    # same per-row init values as _fresh_init (global row order, same
    # seed) so the partition-fed gang tracks a merged-feed train_als
    # run row for row
    x0[:n_users] = (rng.standard_normal((n_users, k))
                    / np.sqrt(k)).astype(np.float32)
    y0[:n_items] = (rng.standard_normal((n_items, k))
                    / np.sqrt(k)).astype(np.float32)

    fingerprint = None
    if checkpoint_hook is not None:
        import zlib

        local_fp = zlib.crc32(
            r.tobytes(),
            zlib.crc32(i.tobytes(),
                       zlib.crc32(u.tobytes(), _DP_LAYOUT_TAG)))
        if n_proc > 1:
            all_fp = np.asarray(multihost_utils.process_allgather(
                np.int64(local_fp))).reshape(-1)
        else:
            all_fp = np.asarray([local_fp], np.int64)
        fingerprint = zlib.crc32(
            all_fp.tobytes(),
            zlib.crc32(np.int64(n_users).tobytes(),
                       zlib.crc32(np.int64(n_items).tobytes(),
                                  _DP_LAYOUT_TAG)))

    start_iter = 0
    rx0 = ry0 = None
    if checkpoint_hook is not None and resume:
        from ..workflow.checkpoint import CheckpointIncompatibleError

        step = checkpoint_hook.latest_step()
        if step is not None and step < params.num_iterations:
            start_iter, tree = checkpoint_hook.restore(step)
            rx = np.asarray(tree["user_factors"])
            ry = np.asarray(tree["item_factors"])
            if rx.shape != x0.shape or ry.shape != y0.shape or \
                    int(np.asarray(tree.get("fingerprint", -1))) \
                    != fingerprint:
                raise CheckpointIncompatibleError(
                    "checkpoint does not match the current partition-"
                    "local layout/data — retrain from scratch")
            rx0, ry0 = rx, ry
    if rx0 is not None:
        x0, y0 = rx0, ry0

    def _rep(host, sharding):
        if n_proc == 1:
            return np.asarray(host)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    def _sharded(host, sharding):
        if n_proc == 1:
            return host
        return jax.make_array_from_process_local_data(
            sharding, host, (host.shape[0] * n_proc,))

    reg = np.float32(params.reg)
    gx = _rep(x0, in_shardings[2])
    gy = _rep(y0, in_shardings[3])
    ev_args = tuple(
        _sharded(a, s) for a, s in zip(
            (u_loc, i_loc, r_loc, w_loc), in_shardings[4:]))

    def dispatch(n, x, y):
        return fn(np.int32(n), reg, x, y, *ev_args)

    chunk = (checkpoint_hook.every_n
             if checkpoint_hook is not None and checkpoint_hook.enabled
             else 0)

    def save(it, x, y):
        checkpoint_hook.save(
            it, {"user_factors": np.asarray(jax.device_get(x)),
                 "item_factors": np.asarray(jax.device_get(y)),
                 "fingerprint": np.int64(fingerprint)})

    if nan_guard:
        from ..common.nan_guard import NaNGuardError

        finite_probe = jax.jit(
            lambda a, b: jnp.isfinite(a).all() & jnp.isfinite(b).all())
        x, y = gx, gy
        for it in range(start_iter, params.num_iterations):
            fault_point("train.sweep")
            x, y = dispatch(1, x, y)
            gang.beat()  # after the dispatch: sweep 1 includes compile
            if not bool(jax.device_get(finite_probe(x, y))):
                raise NaNGuardError(
                    f"stage: {nan_guard_stage}, iteration {it + 1}: "
                    "non-finite factors (check input ratings for "
                    "NaN/Inf or raise the regularization)")
            done = it + 1
            saved = False
            if chunk and done % chunk == 0 \
                    and done < params.num_iterations:
                save(done, x, y)
                saved = True
                gang.beat()
            if done < params.num_iterations \
                    and gang.drain_requested_global():
                if chunk and not saved:
                    save(done, x, y)
                raise gang.GangDrainRequested(done)
    elif chunk and params.num_iterations - start_iter > chunk:
        x, y = gx, gy
        it = start_iter
        while it < params.num_iterations:
            fault_point("train.sweep")
            n = min(chunk, params.num_iterations - it)
            x, y = dispatch(n, x, y)
            gang.beat()
            it += n
            if it < params.num_iterations:
                save(it, x, y)
                gang.beat()  # a save (manager init, barriers) is slow too
                if gang.drain_requested_global():
                    raise gang.GangDrainRequested(it)
    else:
        fault_point("train.sweep")
        x, y = dispatch(params.num_iterations - start_iter, gx, gy)
        gang.beat()
    x, y = jax.device_get((x, y))
    return ALSFactors(
        user_factors=np.asarray(x)[:n_users],
        item_factors=np.asarray(y)[:n_items],
        n_users=n_users,
        n_items=n_items,
    )


def fold_in_factors(y, obs_idx, obs_val, *, reg: float,
                    lambda_scaling: str = "plain",
                    implicit_prefs: bool = False, alpha: float = 1.0,
                    anchor=None, anchor_weight=1.0,
                    yty=None) -> np.ndarray:
    """Closed-form ridge fold-in: solve R rows against FIXED counterpart
    factors ``y`` [n, k] (the ALX fold-in recipe, arxiv 2112.02194 —
    one half-step of ALS for just the touched rows, with the opposite
    side frozen). This is the math of the streaming online-learning
    subsystem (workflow/online.py, docs/operations.md "Online
    learning"): a brand-new user's factor from their first events is
    EXACTLY what a full retrain would produce for them given the
    current counterpart matrix.

    ``obs_idx``: R arrays of counterpart row indices (one per solved
    row); ``obs_val``: R matching float arrays of ratings. Rows ride
    the same per-row normal equations as training (:func:`_grams_rows`
    — zero-padded gather slots contribute nothing), then a batched
    host solve: the systems are [k, k] and R is the handful of
    entities a fold-in increment touches, so a device dispatch would
    cost more than it saves.

    ``anchor`` [R, k] adds a proximal term μ‖x − x_old‖² (μ =
    ``anchor_weight``, scalar or per-row [R]): existing entities blend
    new evidence into their current factor instead of forgetting their
    history (the history itself is not re-read — O(new events), not
    O(log)); rows whose anchor is a brand-new entity's zero row should
    carry μ=0 so they solve the exact cold-start ridge.

    Regularization mirrors training: ``lambda_scaling='nratings'``
    scales λ by each row's (new-)rating count, ``'plain'`` uses λ as
    is; ``implicit_prefs`` adds the shared YᵀY term with
    confidence weights 1+α·r (Hu-Koren-Volinsky, matching
    ``train_als``'s implicit mode against the same ratings).

    Returns the solved rows, [R, k] float32.
    """
    y = np.asarray(y, np.float32)
    n, k = y.shape
    R = len(obs_idx)
    if R == 0:
        return np.zeros((0, k), np.float32)
    C = max((len(ix) for ix in obs_idx), default=0)
    if C == 0 or n == 0:
        return (np.asarray(anchor, np.float32).reshape(R, k)
                if anchor is not None else np.zeros((R, k), np.float32))
    p = np.zeros((R, C, k), np.float32)
    val = np.zeros((R, C), np.float32)
    counts = np.zeros(R, np.float32)
    for r, (ix, v) in enumerate(zip(obs_idx, obs_val)):
        ix = np.asarray(ix, np.int64)
        m = len(ix)
        if m:
            p[r, :m] = y[ix]
            val[r, :m] = np.asarray(v, np.float32)
            counts[r] = m
    grams, rhs = _grams_rows(
        jnp.asarray(p), jnp.asarray(val), implicit=implicit_prefs,
        alpha=alpha, compute_dtype=jnp.float32)
    grams = np.asarray(grams, np.float32)
    rhs = np.asarray(rhs, np.float32)
    if implicit_prefs:
        # the shared YtY term is O(n·k²) over the WHOLE counterpart
        # matrix — the one non-O(new events) piece of an implicit
        # fold-in. Callers folding repeatedly against the same side
        # can pass a precomputed/cached ``yty`` [k, k].
        if yty is None:
            yty = y.T @ y
        grams = grams + np.asarray(yty, np.float32)[None, :, :]
    lam = np.full(R, float(reg), np.float32)
    if lambda_scaling == "nratings":
        lam *= np.maximum(counts, 1.0)
    # no anchor = no proximal term AT ALL: adding mu to the normal
    # matrix without the matching rhs term would be phantom ridge
    # silently shrinking every solution toward zero. anchor_weight may
    # be per-row ([R]) — callers zero it for rows whose anchor is the
    # meaningless zero row of a brand-new entity, keeping those at the
    # exact cold-start ridge the contract promises.
    if anchor is None:
        mu = np.zeros(R, np.float32)
    else:
        mu = np.maximum(np.broadcast_to(
            np.asarray(anchor_weight, np.float32), (R,)), 0.0)
    a = grams + (lam + mu)[:, None, None] * np.eye(k, dtype=np.float32)
    if anchor is not None:
        rhs = rhs + mu[:, None] * np.asarray(anchor,
                                             np.float32).reshape(R, k)
    # batched [k, k] solves want an explicit trailing rhs column
    return np.linalg.solve(a, rhs[..., None])[..., 0].astype(np.float32)


def predict_rmse(factors: ALSFactors, user_idx, item_idx, rating) -> float:
    """Host-side RMSE over a COO triple (eval helper)."""
    x = factors.user_factors[np.asarray(user_idx)]
    y = factors.item_factors[np.asarray(item_idx)]
    pred = np.sum(x * y, axis=1)
    err = pred - np.asarray(rating, dtype=np.float32)
    return float(np.sqrt(np.mean(err**2)))
