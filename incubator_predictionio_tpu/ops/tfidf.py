"""TF-IDF vectorization (host side) for the text-classification template.

Reference behaviour: the text-classifier template tokenizes, builds TF-IDF
vectors with Spark MLlib's HashingTF/IDF, then trains NB/LR
(SURVEY.md §2.8 row 4). Host-side prep is the right split on TPU too:
tokenization is string work (CPU), the [N,D] matrix then feeds the
mesh-sharded linear kernels. Hashing keeps D static for XLA.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def tokenize(text: str, ngram: int = 1) -> list[str]:
    toks = [t.lower() for t in _TOKEN_RE.findall(text)]
    if ngram <= 1:
        return toks
    out = list(toks)
    for n in range(2, ngram + 1):
        out += [" ".join(toks[j : j + n]) for j in range(len(toks) - n + 1)]
    return out


def _hash_token(tok: str, n_features: int) -> int:
    # Deterministic (process-independent) FNV-1a, mirroring HashingTF's
    # fixed-hash behaviour so models survive restarts.
    h = 2166136261
    for b in tok.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % n_features


@dataclasses.dataclass
class TfIdfVectorizer:
    n_features: int = 4096
    ngram: int = 1
    idf: Optional[np.ndarray] = None  # [D], set by fit
    # token → hashed bucket, filled lazily: the per-byte FNV only runs
    # once per DISTINCT token (corpus vocabularies are orders of
    # magnitude smaller than their token streams — memoizing took the
    # 20-newsgroups-scale fit from ~7s to well under a second)
    _hash_cache: dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False)

    def __getstate__(self):
        # The cache is pure derived data; pickling it would inflate every
        # persisted model blob by the corpus vocabulary.
        state = self.__dict__.copy()
        state["_hash_cache"] = {}
        return state

    def _doc_hashed_indices(self, doc: str) -> Optional[np.ndarray]:
        """Hashed bucket id per token occurrence of one doc, through
        the memoized token→bucket cache — the ONE Python tokenizer loop
        (term_frequencies and the COO fallback both consume it, keeping
        them bit-identical to each other and to the native passes)."""
        toks = tokenize(doc, self.ngram)
        if not toks:
            return None
        D = self.n_features
        cache = self._hash_cache
        idxs = np.empty(len(toks), np.int64)
        for j, tok in enumerate(toks):
            h = cache.get(tok)
            if h is None:
                h = _hash_token(tok, D)
                # Cap: transform() runs per serving query on arbitrary
                # user text — an uncapped cache grows monotonically
                # until OOM on a long-lived server.
                if len(cache) < 1_000_000:
                    cache[tok] = h
            idxs[j] = h
        return idxs

    def term_frequencies(self, docs: Sequence[str],
                         use_native: bool | None = None,
                         want_df: bool = False):
        """[N,D] counts; with ``want_df`` returns ``(tf, df)`` where df
        is the per-bucket document frequency (== count_nonzero(tf, 0),
        accumulated in the same native pass when available)."""
        D = self.n_features
        # Batch path: the C++ tokenizer+hasher (native.tfidf_tf) is
        # bit-identical to the loop below and ~20x faster; single-doc
        # serving queries stay in Python (the memoized cache wins there
        # and the ctypes call overhead doesn't).
        if use_native is True or (use_native is None and len(docs) > 4):
            try:
                from ..native import NativeUnavailable, tfidf_tf
                return tfidf_tf(docs, D, self.ngram, want_df=want_df)
            except NativeUnavailable:
                if use_native is True:
                    raise
        x = np.zeros((len(docs), D), np.float32)
        for row, doc in enumerate(docs):
            idxs = self._doc_hashed_indices(doc)
            if idxs is not None:
                x[row] = np.bincount(idxs, minlength=D)
        if want_df:
            return x, np.count_nonzero(x, axis=0).astype(np.int64)
        return x

    def tf_coo_block(self, docs: Sequence[str],
                     use_native: bool | None = None):
        """Per-doc COO of one document block WITHOUT touching fit state:
        ``(doc_ptr [N+1], feat [nnz] int32, counts [nnz] float32, df
        [D] int64)`` — the pure building block that fit_tf_coo runs once
        over the whole corpus and the streaming input pipeline runs per
        chunk from worker threads (thread-safe: the only shared state is
        the memoized token cache, whose entries are idempotent). Block
        COOs concatenate to the full-corpus COO bit-for-bit; block dfs
        sum to the corpus df exactly (int64)."""
        D = self.n_features
        try:
            if use_native is False:
                from ..native import NativeUnavailable
                raise NativeUnavailable("fallback forced (use_native=False)")
            from ..native import NativeUnavailable, tfidf_tf_coo
            return tfidf_tf_coo(docs, D, self.ngram, want_df=True)
        except NativeUnavailable:
            if use_native is True:
                raise
        doc_ptr = np.zeros(len(docs) + 1, np.int64)
        feats = []
        cnts = []
        df = np.zeros(D, np.int64)
        for row, doc in enumerate(docs):
            idxs = self._doc_hashed_indices(doc)
            added = 0
            if idxs is not None:
                # sparse per-doc aggregation (ascending, like C++) —
                # no D-length scratch per doc
                nz, nz_counts = np.unique(idxs, return_counts=True)
                feats.append(nz.astype(np.int32))
                cnts.append(nz_counts.astype(np.float32))
                df[nz] += 1
                added = len(nz)
            doc_ptr[row + 1] = doc_ptr[row] + added
        feat = (np.concatenate(feats) if feats
                else np.empty(0, np.int32))
        counts = (np.concatenate(cnts) if cnts
                  else np.empty(0, np.float32))
        return doc_ptr, feat, counts, df

    def set_idf_from_df(self, df: np.ndarray, n_docs: int) -> np.ndarray:
        """Finalize the fit from accumulated document frequencies
        (MLlib IDF: log((n+1)/(df+1))) — the last step of both the
        one-shot fit and the streamed fit."""
        self.idf = np.log((n_docs + 1.0) / (df + 1.0)).astype(np.float32)
        return self.idf

    def fit_tf_coo(self, docs: Sequence[str],
                   use_native: bool | None = None):
        """Fit the IDF and return per-doc (feature, count) pairs —
        ``(doc_ptr [N+1], feat [nnz] int32, counts [nnz] float32)`` in
        ascending bucket order per doc — WITHOUT materializing the
        dense [N, D] matrix anywhere. Linear trainers reduce over docs,
        so the token-level COO (~150 distinct buckets/doc) is all that
        ever needs to exist on the host or cross the accelerator link
        (models/text_classification.TextNBAlgorithm trains straight
        from this via a device segment-sum)."""
        doc_ptr, feat, counts, df = self.tf_coo_block(docs, use_native)
        self.set_idf_from_df(df, len(docs))
        return doc_ptr, feat, counts

    def fit_tf(self, docs: Sequence[str]) -> np.ndarray:
        """Fit the IDF and return the RAW term-frequency matrix without
        materializing the scaled one. For linear trainers the column
        scale commutes with the row reduction (onehotᵀ@(tf·idf) =
        (onehotᵀ@tf)·idf), so the [N,D] multiply+alloc — the dominant
        host cost at corpus scale — can fold into the [C,D] stats
        instead (models/text_classification.TextNBAlgorithm)."""
        tf, df = self.term_frequencies(docs, want_df=True)
        n = len(docs)
        # MLlib IDF: log((n+1)/(df+1))
        self.idf = np.log((n + 1.0) / (df + 1.0)).astype(np.float32)
        return tf

    def fit_transform(self, docs: Sequence[str]) -> np.ndarray:
        tf = self.fit_tf(docs)
        return tf * self.idf

    def transform(self, docs: Sequence[str]) -> np.ndarray:
        if self.idf is None:
            raise ValueError("vectorizer is not fitted")
        return self.term_frequencies(docs) * self.idf

    def to_arrays(self) -> dict:
        return {
            "idf": self.idf,
            "n_features": np.asarray(self.n_features),
            "ngram": np.asarray(self.ngram),
        }

    @classmethod
    def from_arrays(cls, arrays: dict) -> "TfIdfVectorizer":
        return cls(
            n_features=int(arrays["n_features"]),
            ngram=int(arrays["ngram"]),
            idf=np.asarray(arrays["idf"], np.float32),
        )
