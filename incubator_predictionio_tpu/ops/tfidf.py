"""TF-IDF vectorization (host side) for the text-classification template.

Reference behaviour: the text-classifier template tokenizes, builds TF-IDF
vectors with Spark MLlib's HashingTF/IDF, then trains NB/LR
(SURVEY.md §2.8 row 4). Host-side prep is the right split on TPU too:
tokenization is string work (CPU), the [N,D] matrix then feeds the
mesh-sharded linear kernels. Hashing keeps D static for XLA.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable, Optional, Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def tokenize(text: str, ngram: int = 1) -> list[str]:
    toks = [t.lower() for t in _TOKEN_RE.findall(text)]
    if ngram <= 1:
        return toks
    out = list(toks)
    for n in range(2, ngram + 1):
        out += [" ".join(toks[j : j + n]) for j in range(len(toks) - n + 1)]
    return out


def _hash_token(tok: str, n_features: int) -> int:
    # Deterministic (process-independent) FNV-1a, mirroring HashingTF's
    # fixed-hash behaviour so models survive restarts.
    h = 2166136261
    for b in tok.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % n_features


@dataclasses.dataclass
class TfIdfVectorizer:
    n_features: int = 4096
    ngram: int = 1
    idf: Optional[np.ndarray] = None  # [D], set by fit

    def term_frequencies(self, docs: Sequence[str]) -> np.ndarray:
        x = np.zeros((len(docs), self.n_features), np.float32)
        for row, doc in enumerate(docs):
            for tok in tokenize(doc, self.ngram):
                x[row, _hash_token(tok, self.n_features)] += 1.0
        return x

    def fit_transform(self, docs: Sequence[str]) -> np.ndarray:
        tf = self.term_frequencies(docs)
        df = (tf > 0).sum(axis=0)
        n = len(docs)
        # MLlib IDF: log((n+1)/(df+1))
        self.idf = np.log((n + 1.0) / (df + 1.0)).astype(np.float32)
        return tf * self.idf

    def transform(self, docs: Sequence[str]) -> np.ndarray:
        if self.idf is None:
            raise ValueError("vectorizer is not fitted")
        return self.term_frequencies(docs) * self.idf

    def to_arrays(self) -> dict:
        return {
            "idf": self.idf,
            "n_features": np.asarray(self.n_features),
            "ngram": np.asarray(self.ngram),
        }

    @classmethod
    def from_arrays(cls, arrays: dict) -> "TfIdfVectorizer":
        return cls(
            n_features=int(arrays["n_features"]),
            ngram=int(arrays["ngram"]),
            idf=np.asarray(arrays["idf"], np.float32),
        )
