"""Linear-model kernels: multinomial Naive Bayes + logistic regression.

The reference's classification templates call MLlib NaiveBayes /
LogisticRegressionWithLBFGS (reference: examples/scala-parallel-
classification, SURVEY.md §2.8 row 2; the distributed treeAggregate of
sufficient stats / gradients lives inside MLlib). TPU-native design:

- NB sufficient stats are one [C,N]×[N,D] matmul (one-hot labelsᵀ ×
  features) — examples row-sharded over the mesh data axis, XLA emits the
  psum over ICI from the sharding annotations (pjit, no manual
  collectives).
- LR is full-batch L-BFGS (optax) with the loss/grad pjit'd the same
  way: per-device partial sums, psum'd gradients — the moral equivalent
  of MLlib's treeAggregate pass, minus the shuffle.

Numerical parity notes (SURVEY.md §7 hard parts): NB smoothing is MLlib's
additive `lambda` (default 1.0); LR matches the template's L2-regularized
multinomial softmax with intercept (regParam applied to weights only).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, default_mesh, fast_put, pad_rows


# ---------------------------------------------------------------------------
# Naive Bayes (multinomial, additive smoothing)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NaiveBayesModel:
    log_prior: np.ndarray  # [C]
    log_likelihood: np.ndarray  # [C, D]
    n_classes: int

    def predict_log_joint(self, x: np.ndarray) -> np.ndarray:
        return x @ self.log_likelihood.T + self.log_prior  # [B, C]


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _nb_stats(x, y, w, n_classes: int):
    # x may arrive bfloat16 or uint8 (lossless narrow uploads, see
    # train_naive_bayes); integer wire dtypes widen to bf16 here so the
    # one-hot einsum feeds the MXU natively, accumulating in float32
    # either way.
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(jnp.bfloat16)
    onehot = jax.nn.one_hot(y, n_classes, dtype=x.dtype) * w[:, None].astype(x.dtype)
    feat = jnp.einsum("nc,nd->cd", onehot, x,
                      preferred_element_type=jnp.float32)  # [C, D]
    counts = onehot.astype(jnp.float32).sum(axis=0)  # [C]
    return feat, counts


def train_naive_bayes(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
    col_scale: Optional[np.ndarray] = None,
) -> NaiveBayesModel:
    """x [N,D] nonneg features, y [N] int labels. Mesh-sharded stats.

    ``col_scale`` [D] applies a per-feature scale (TF-IDF's idf) to the
    CLASS STATS instead of the examples — mathematically the same as
    training on ``x * col_scale`` (the scale commutes with the row
    reduction) without ever materializing that [N,D] product.
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    # Halve the host->device bytes when it costs nothing: attribute
    # matrices are typically small counts/ratings that round-trip
    # bfloat16 exactly. Only on an accelerator (there is no transfer to
    # shrink on the CPU backend, just cast overhead — same gate as
    # als.py's compute_dtype "auto"), and only when every value is
    # exactly representable; the stats einsum accumulates in float32
    # regardless.
    if mesh.devices.flat[0].platform == "tpu":
        # Narrowest lossless wire dtype, widened on device by _nb_stats:
        # small nonneg integer counts (the multinomial NB domain) fit
        # uint8 — a QUARTER of the f32 bytes; anything bf16-exact still
        # halves them.
        x_int = x.astype(np.uint8)
        if np.array_equal(x_int.astype(np.float32), x):
            x = x_int
        else:
            xb = x.astype(jnp.bfloat16)
            if np.array_equal(xb.astype(np.float32), x):
                x = xb
    w = np.ones(x.shape[0], np.float32)
    xp, yp, wp = pad_rows(x, n_dev), pad_rows(y, n_dev), pad_rows(w, n_dev)
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    xp = fast_put(xp, shard2)
    yp = fast_put(yp, shard1)
    wp = fast_put(wp, shard1)
    feat, counts = jax.device_get(_nb_stats(xp, yp, wp, n_classes))
    if col_scale is not None:
        feat = feat * np.asarray(col_scale, np.float32)

    total = counts.sum()
    log_prior = np.log((counts + 1e-12) / max(total, 1e-12))
    num = feat + smoothing
    log_likelihood = np.log(num) - np.log(num.sum(axis=1, keepdims=True))
    return NaiveBayesModel(
        log_prior=log_prior.astype(np.float32),
        log_likelihood=log_likelihood.astype(np.float32),
        n_classes=n_classes,
    )


@functools.partial(jax.jit, static_argnames=("n_classes", "n_features"))
def _nb_stats_coo(cls_idx, feat_idx, counts, n_classes: int,
                  n_features: int):
    """[C, D] class-feature sums from COO token entries via one
    scatter-add over the combined (class, feature) index. Padding
    entries carry count 0 (adds nothing to bucket 0)."""
    idx = cls_idx.astype(jnp.int32) * n_features + feat_idx.astype(jnp.int32)
    feat = jnp.zeros((n_classes * n_features,), jnp.float32)
    feat = feat.at[idx].add(counts.astype(jnp.float32))
    return feat.reshape(n_classes, n_features)


def train_naive_bayes_coo(
    doc_ptr: np.ndarray,
    feat_idx: np.ndarray,
    counts: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    n_features: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
    col_scale: Optional[np.ndarray] = None,
) -> NaiveBayesModel:
    """NB from the tokenizer's COO output (ops/tfidf.fit_tf_coo): the
    dense [N, D] matrix never exists — only the ~150 distinct buckets
    per doc cross the host->device link (13x fewer bytes at the
    20-newsgroups shape), and the class-feature stats come from one
    device scatter-add. Numerically equivalent to train_naive_bayes on
    the materialized matrix: the per-class sum is the same additions in
    a different association order, both accumulating f32 (tests pin
    near-identity; ulp-level reduction-order differences are possible).

    Uploads narrow where lossless: feature ids as uint16 when D fits,
    class ids as uint8 when C fits, counts as uint16 when all counts do
    (per-doc term frequencies overwhelmingly fit).
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    y = np.asarray(y, np.int32)
    cls_per_entry = np.repeat(y, np.diff(np.asarray(doc_ptr)))
    feat_idx = np.asarray(feat_idx)
    counts = np.asarray(counts, np.float32)

    # lossless narrow uploads (widened on device by _nb_stats_coo)
    if n_features <= np.iinfo(np.uint16).max + 1:
        feat_idx = feat_idx.astype(np.uint16)
    if n_classes <= np.iinfo(np.uint8).max + 1:
        cls_per_entry = cls_per_entry.astype(np.uint8)
    cnt_up = counts
    if counts.size and float(counts.max()) <= np.iinfo(np.uint16).max \
            and np.array_equal(counts.astype(np.uint16), counts):
        cnt_up = counts.astype(np.uint16)

    cp = pad_rows(cls_per_entry, n_dev)
    fp = pad_rows(feat_idx, n_dev)
    wp = pad_rows(cnt_up, n_dev)      # pad counts are 0: contribute nothing
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    cp = fast_put(cp, shard1)
    fp = fast_put(fp, shard1)
    wp = fast_put(wp, shard1)
    feat = np.asarray(jax.device_get(
        _nb_stats_coo(cp, fp, wp, n_classes, n_features)))
    if col_scale is not None:
        feat = feat * np.asarray(col_scale, np.float32)

    class_counts = np.bincount(y, minlength=n_classes).astype(np.float64)
    total = class_counts.sum()
    log_prior = np.log((class_counts + 1e-12) / max(total, 1e-12))
    num = feat + smoothing
    log_likelihood = np.log(num) - np.log(num.sum(axis=1, keepdims=True))
    return NaiveBayesModel(
        log_prior=log_prior.astype(np.float32),
        log_likelihood=log_likelihood.astype(np.float32),
        n_classes=n_classes,
    )


# ---------------------------------------------------------------------------
# Logistic regression (multinomial softmax, L2, L-BFGS)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # [D, C]
    intercept: np.ndarray  # [C]
    n_classes: int

    def predict_logits(self, x: np.ndarray) -> np.ndarray:
        return x @ self.weights + self.intercept

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        z = self.predict_logits(x)
        z = z - z.max(axis=-1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=-1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("n_classes",),
                   donate_argnums=())
def _lr_fit(xp, yp, maskp, n, reg, tol, max_iters, n_classes: int):
    """The ENTIRE L-BFGS optimization in one jit (lax.while_loop with
    the convergence test on-device). Module-level so the compiled
    executable is REUSED across train calls at the same shapes — a
    per-call closure would retrace+recompile every `pio train`, and a
    host-side step loop would pay a dispatch+readback round trip per
    iteration (~1s/iter through a remote-PJRT tunnel, 1000x the actual
    step cost at template shapes)."""
    import optax

    # narrow wire dtypes (uint8 / lossless bf16) widen back to f32
    # BEFORE any math: results are bit-identical to an f32 upload
    xp = xp.astype(jnp.float32)

    d = xp.shape[1]

    def loss_fn(params):
        w, b = params
        logits = xp @ w + b  # [Np, C] row-sharded
        logp = jax.nn.log_softmax(logits)
        # one-hot contraction, NOT take_along_axis: a per-row gather runs
        # at the TPU gather unit's fixed ~420M rows/s (BASELINE.md
        # roofline) — 6x the cost of this elementwise mask at bench shape.
        onehot = jax.nn.one_hot(yp, n_classes, dtype=logp.dtype)
        nll = -(logp * onehot).sum(axis=1)
        data = jnp.sum(nll * maskp) / n
        return data + 0.5 * reg * jnp.sum(w * w)

    # Backtracking linesearch instead of the default zoom: zoom's
    # while_loop lowers to ~1.7s/step at 2M-example shape (hundreds of
    # serialized loss evals); backtracking converges the template
    # configurations identically at ~3ms/step.
    opt = optax.lbfgs(linesearch=optax.scale_by_backtracking_linesearch(
        max_backtracking_steps=20, store_grad=True))
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        it, params, state, prev, _ = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        gnorm = optax.tree.norm(grad)
        done = (jnp.abs(prev - value)
                < tol * jnp.maximum(1.0, jnp.abs(prev))) & (gnorm < 1e-4)
        return it + 1, params, state, value, done

    def cond(carry):
        it, _, _, _, done = carry
        return (it < max_iters) & ~done

    params = (jnp.zeros((d, n_classes)), jnp.zeros((n_classes,)))
    carry = (jnp.int32(0), params, opt.init(params), jnp.float32(jnp.inf),
             jnp.bool_(False))
    carry = jax.lax.while_loop(cond, step, carry)
    return carry[1]


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    reg: float = 0.0,
    max_iters: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
) -> LogisticRegressionModel:
    """Full-batch multinomial LR via optax L-BFGS; data row-sharded over
    the mesh, gradient psum inserted by XLA."""
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n = x.shape[0]
    if mesh.devices.flat[0].platform == "tpu":
        # Lossless narrow wire (same gate as train_naive_bayes); _lr_fit
        # widens back to f32 on device FIRST, so the optimization math
        # and its results are bit-identical to an f32 upload.
        x_int = x.astype(np.uint8)
        if np.array_equal(x_int.astype(np.float32), x):
            x = x_int
        else:
            xb = x.astype(jnp.bfloat16)
            if np.array_equal(xb.astype(np.float32), x):
                x = xb
    mask = pad_rows(np.ones(n, np.float32), n_dev)
    xp = pad_rows(x, n_dev)
    yp = pad_rows(y, n_dev)
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    xp = fast_put(xp, shard2)
    yp = fast_put(yp, shard1)
    maskp = fast_put(mask, shard1)

    params = _lr_fit(xp, yp, maskp, jnp.float32(n), jnp.float32(reg),
                     jnp.float32(tol), jnp.int32(max_iters), n_classes)
    w, b = jax.device_get(params)
    return LogisticRegressionModel(
        weights=np.asarray(w, np.float32),
        intercept=np.asarray(b, np.float32),
        n_classes=n_classes,
    )
