"""Linear-model kernels: multinomial Naive Bayes + logistic regression.

The reference's classification templates call MLlib NaiveBayes /
LogisticRegressionWithLBFGS (reference: examples/scala-parallel-
classification, SURVEY.md §2.8 row 2; the distributed treeAggregate of
sufficient stats / gradients lives inside MLlib). TPU-native design:

- NB sufficient stats are one [C,N]×[N,D] matmul (one-hot labelsᵀ ×
  features) — examples row-sharded over the mesh data axis, XLA emits the
  psum over ICI from the sharding annotations (pjit, no manual
  collectives).
- LR is full-batch L-BFGS (optax) with the loss/grad pjit'd the same
  way: per-device partial sums, psum'd gradients — the moral equivalent
  of MLlib's treeAggregate pass, minus the shuffle.

Numerical parity notes (SURVEY.md §7 hard parts): NB smoothing is MLlib's
additive `lambda` (default 1.0); LR matches the template's L2-regularized
multinomial softmax with intercept (regParam applied to weights only).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, default_mesh, fast_put, pad_rows
from ..workflow.input_pipeline import (
    PipelineConfig, PipelineStats, chunk_ranges, prefetch, run_pipeline,
)


def _narrow_wire(x: np.ndarray, on_tpu: bool):
    """Narrowest LOSSLESS wire dtype for a feature block: small nonneg
    integer counts fit uint8 (a quarter of the f32 bytes); anything
    bf16-exact still halves them. Only on an accelerator — there is no
    transfer to shrink on the CPU backend, just cast overhead. The
    device side widens back to f32 BEFORE any math, so results are
    bit-identical to an f32 upload."""
    if not on_tpu:
        return x
    x_int = x.astype(np.uint8)
    if np.array_equal(x_int.astype(np.float32), x):
        return x_int
    xb = x.astype(jnp.bfloat16)
    if np.array_equal(xb.astype(np.float32), x):
        return xb
    return x


# ---------------------------------------------------------------------------
# Naive Bayes (multinomial, additive smoothing)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NaiveBayesModel:
    log_prior: np.ndarray  # [C]
    log_likelihood: np.ndarray  # [C, D]
    n_classes: int
    # Sufficient statistics, carried so the streaming fold-in
    # (workflow/online.py) can fold new labeled examples in EXACTLY —
    # NB's log params are a pure function of (feat, counts, smoothing),
    # so counts + increments == a full retrain on old∪new. None on
    # models persisted before these fields existed (fold-in then
    # declines and asks for one retrain) and on col_scale (TF-IDF)
    # trainings, where the scale itself shifts with new documents.
    feat_counts: Optional[np.ndarray] = None   # [C, D] pre-smoothing
    class_counts: Optional[np.ndarray] = None  # [C]
    smoothing: float = 1.0

    def predict_log_joint(self, x: np.ndarray) -> np.ndarray:
        return x @ self.log_likelihood.T + self.log_prior  # [B, C]


def nb_model_from_counts(feat: np.ndarray, counts: np.ndarray,
                         n_classes: int, smoothing: float,
                         keep_counts: bool = True) -> NaiveBayesModel:
    """(class-feature sums, class counts) → NaiveBayesModel. THE one
    construction every NB trainer and the fold-in path share, so the
    smoothing/normalization math cannot drift between them."""
    # arithmetic runs in the CALLER's dtype (f32 device stats, f64
    # bincounts) so this refactor is bit-identical to the construction
    # it replaced in each trainer
    total = counts.sum()
    log_prior = np.log((counts + 1e-12) / max(total, 1e-12))
    num = feat + smoothing
    log_likelihood = np.log(num) - np.log(num.sum(axis=1, keepdims=True))
    return NaiveBayesModel(
        log_prior=log_prior.astype(np.float32),
        log_likelihood=log_likelihood.astype(np.float32),
        n_classes=n_classes,
        feat_counts=(np.asarray(feat, np.float32)
                     if keep_counts else None),
        class_counts=(np.asarray(counts, np.float32)
                      if keep_counts else None),
        smoothing=float(smoothing),
    )


def nb_fold_in(model: NaiveBayesModel, x: np.ndarray, y: np.ndarray,
               x_remove=None, y_remove=None) -> Optional[NaiveBayesModel]:
    """Exact incremental NB update: add the new examples' sufficient
    statistics (and SUBTRACT ``x_remove``/``y_remove`` — the previous
    example of an entity being re-labeled, so an update replaces
    instead of double-counting) and rebuild the log params — bit-for-
    bit what a retrain on the updated example set would produce
    (integer-count features sum exactly in f32). Returns None when the
    model carries no stored counts (legacy blob or col-scaled
    training): the caller logs and waits for a retrain. Never mutates
    ``model``."""
    feat = getattr(model, "feat_counts", None)
    counts = getattr(model, "class_counts", None)
    if feat is None or counts is None:
        return None
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int64)
    if x.ndim != 2 or x.shape[1] != feat.shape[1] or len(x) != len(y):
        return None

    def stats(xs, ys):
        onehot = np.zeros((len(ys), model.n_classes), np.float32)
        onehot[np.arange(len(ys)), ys] = 1.0
        return onehot.T @ xs, onehot.sum(axis=0)

    f_add, c_add = stats(x, y)
    feat = feat + f_add
    counts = counts + c_add
    if x_remove is not None and len(x_remove):
        f_sub, c_sub = stats(np.asarray(x_remove, np.float32),
                             np.asarray(y_remove, np.int64))
        # clip: a corrupt removal record must never drive counts
        # negative (log of a negative smoothed count is NaN)
        feat = np.maximum(feat - f_sub, 0.0)
        counts = np.maximum(counts - c_sub, 0.0)
    return nb_model_from_counts(
        feat, counts, model.n_classes, getattr(model, "smoothing", 1.0))


def _nb_stats_body(x, y, w, n_classes: int):
    # x may arrive bfloat16 or uint8 (lossless narrow uploads, see
    # train_naive_bayes); integer wire dtypes widen to bf16 here so the
    # one-hot einsum feeds the MXU natively, accumulating in float32
    # either way.
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(jnp.bfloat16)
    onehot = jax.nn.one_hot(y, n_classes, dtype=x.dtype) * w[:, None].astype(x.dtype)
    feat = jnp.einsum("nc,nd->cd", onehot, x,
                      preferred_element_type=jnp.float32)  # [C, D]
    counts = onehot.astype(jnp.float32).sum(axis=0)  # [C]
    return feat, counts


_nb_stats = functools.partial(jax.jit, static_argnames=("n_classes",))(
    _nb_stats_body)


@functools.partial(jax.jit, static_argnames=("n_classes",),
                   donate_argnums=(0, 1))
def _nb_stats_acc(feat_acc, counts_acc, x, y, w, n_classes: int):
    """One streamed chunk folded into the running [C,D]/[C] stats.
    Accumulators are donated so the ring's steady-state HBM is the
    in-flight chunks plus ONE accumulator. Zero-weight pad rows add
    exact zeros; with count-valued features every partial sum is an
    integer exactly representable in f32, so the chunked reduction
    matches the single-shot einsum bit-for-bit."""
    feat, counts = _nb_stats_body(x, y, w, n_classes)
    # third output: a tiny NON-donated per-chunk value — the ring blocks
    # on it as its completion token (the accumulators themselves are
    # donated into the NEXT step before the ring ever waits on them)
    return feat_acc + feat, counts_acc + counts, counts


def _stream_nb_dense(x, y, n_classes, mesh, on_tpu,
                     cfg: PipelineConfig, stats: Optional[PipelineStats]):
    """Double-buffered featurize→upload→accumulate over row chunks.
    Returns host (feat [C,D], counts [C]) identical to the single-shot
    path (see _nb_stats_acc exactness note)."""
    n_dev = int(np.prod(list(mesh.shape.values())))
    n = x.shape[0]
    # fixed chunk geometry: one compiled program per wire dtype
    step = max(n_dev, -(-min(cfg.chunk_rows, n) // n_dev) * n_dev)
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
    shard1 = NamedSharding(mesh, P(DATA_AXIS))

    def featurize(rng):
        s, e = rng
        # per-chunk narrowing: each chunk ships its own narrowest
        # lossless dtype (a late non-uint8 chunk costs one extra
        # compile, never correctness)
        xc = pad_rows(_narrow_wire(x[s:e], on_tpu), step)
        yc = pad_rows(y[s:e], step)
        wc = pad_rows(np.ones(e - s, np.float32), step)  # pad w=0: no-op rows
        return xc, yc, wc

    def upload(chunk):
        xc, yc, wc = chunk
        return (fast_put(xc, shard2), fast_put(yc, shard1),
                fast_put(wc, shard1))

    acc = (jnp.zeros((n_classes, x.shape[1]), jnp.float32),
           jnp.zeros((n_classes,), jnp.float32))

    def consume(dev):
        nonlocal acc
        feat_acc, counts_acc, ready = _nb_stats_acc(
            acc[0], acc[1], *dev, n_classes)
        acc = (feat_acc, counts_acc)
        return ready

    chunks = prefetch(chunk_ranges(n, step), featurize,
                      workers=cfg.workers, lookahead=cfg.depth + 1,
                      stats=stats)
    run_pipeline(chunks, upload, consume, depth=cfg.depth, stats=stats)
    return jax.device_get(acc)


def train_naive_bayes(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
    col_scale: Optional[np.ndarray] = None,
    pipeline: Optional[PipelineConfig] = None,
    pipeline_stats: Optional[PipelineStats] = None,
) -> NaiveBayesModel:
    """x [N,D] nonneg features, y [N] int labels. Mesh-sharded stats.

    ``col_scale`` [D] applies a per-feature scale (TF-IDF's idf) to the
    CLASS STATS instead of the examples — mathematically the same as
    training on ``x * col_scale`` (the scale commutes with the row
    reduction) without ever materializing that [N,D] product.

    ``pipeline`` (default: env via PipelineConfig.from_env): when the
    input is large enough, the narrowing cast, host→device upload, and
    on-device stats pass run as an overlapped chunk stream
    (workflow/input_pipeline) instead of three serial full-data phases;
    ``mode='off'`` pins the single-shot path. With count-valued
    features (the multinomial NB domain) the two paths are bit-identical
    (exact f32 integer partial sums).
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    on_tpu = mesh.devices.flat[0].platform == "tpu"
    cfg = pipeline or PipelineConfig.from_env()
    if cfg.enabled_for(x.shape[0]):
        feat, counts = _stream_nb_dense(x, y, n_classes, mesh, on_tpu,
                                        cfg, pipeline_stats)
    else:
        # Single-shot fallback: narrow the whole matrix (halve/quarter
        # the host->device bytes when it costs nothing — only on an
        # accelerator, same gate as als.py's compute_dtype "auto"), one
        # put per operand, one stats dispatch.
        x = _narrow_wire(x, on_tpu)
        w = np.ones(x.shape[0], np.float32)
        xp, yp, wp = pad_rows(x, n_dev), pad_rows(y, n_dev), pad_rows(w, n_dev)
        shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
        shard1 = NamedSharding(mesh, P(DATA_AXIS))
        xp = fast_put(xp, shard2)
        yp = fast_put(yp, shard1)
        wp = fast_put(wp, shard1)
        feat, counts = jax.device_get(_nb_stats(xp, yp, wp, n_classes))
    if col_scale is not None:
        feat = feat * np.asarray(col_scale, np.float32)

    # col-scaled (TF-IDF) stats are not fold-in-able: the scale itself
    # moves with new documents, so stored counts would lie
    return nb_model_from_counts(feat, counts, n_classes, smoothing,
                                keep_counts=col_scale is None)


@functools.partial(jax.jit, static_argnames=("n_classes", "n_features"))
def _nb_stats_coo(cls_idx, feat_idx, counts, n_classes: int,
                  n_features: int):
    """[C, D] class-feature sums from COO token entries via one
    scatter-add over the combined (class, feature) index. Padding
    entries carry count 0 (adds nothing to bucket 0)."""
    idx = cls_idx.astype(jnp.int32) * n_features + feat_idx.astype(jnp.int32)
    feat = jnp.zeros((n_classes * n_features,), jnp.float32)
    feat = feat.at[idx].add(counts.astype(jnp.float32))
    return feat.reshape(n_classes, n_features)


@functools.partial(jax.jit, static_argnames=("n_features",),
                   donate_argnums=(0,))
def _nb_stats_coo_acc(acc_flat, cls_idx, feat_idx, counts, n_features: int):
    """One streamed COO entry chunk scatter-added into the running flat
    [C*D] stats (donated). Pad entries carry count 0 — adding +0.0 at
    bucket 0 is an exact no-op — and per-doc term counts are integers,
    so the chunked scatter matches the single-shot one bit-for-bit."""
    idx = cls_idx.astype(jnp.int32) * n_features + feat_idx.astype(jnp.int32)
    new_acc = acc_flat.at[idx].add(counts.astype(jnp.float32))
    # second output: non-donated completion token for the ring (see
    # _nb_stats_acc)
    return new_acc, counts.astype(jnp.float32).sum()


def _narrow_coo_chunk(cls_e, feat_e, cnt_e, n_classes: int, n_features: int):
    """Lossless narrow wire dtypes for one COO entry chunk (widened on
    device): feature ids uint16 when D fits, class ids uint8 when C
    fits, counts uint16 when every count does."""
    if n_features <= np.iinfo(np.uint16).max + 1:
        feat_e = feat_e.astype(np.uint16)
    if n_classes <= np.iinfo(np.uint8).max + 1:
        cls_e = cls_e.astype(np.uint8)
    if cnt_e.size and float(cnt_e.max()) <= np.iinfo(np.uint16).max \
            and np.array_equal(cnt_e.astype(np.uint16), cnt_e):
        cnt_e = cnt_e.astype(np.uint16)
    return cls_e, feat_e, cnt_e


def rebatch_entries(chunks: Iterable[tuple], chunk_entries: int):
    """Re-chunk a ragged stream of (cls, feat, counts) COO entry blocks
    into FIXED-size entry chunks (the last one short) so the device
    consumer compiles one program instead of one per ragged shape.
    Pure host-side carry logic on the consumer thread; entry order is
    preserved exactly."""
    step = max(1, int(chunk_entries))
    carry: list[tuple] = []
    held = 0

    def drain(parts, take):
        out, rest, got = [], [], 0
        for p in parts:
            n = len(p[0])
            if got + n <= take:
                out.append(p)
                got += n
            else:
                k = take - got
                if k > 0:
                    out.append(tuple(a[:k] for a in p))
                    rest.append(tuple(a[k:] for a in p))
                    got = take
                else:
                    rest.append(p)
        cat = tuple(np.concatenate([p[j] for p in out])
                    if len(out) != 1 else out[0][j] for j in range(3))
        return cat, rest

    for block in chunks:
        carry.append(block)
        held += len(block[0])
        while held >= step:
            full, carry = drain(carry, step)
            held -= step
            yield full
    if held:
        last, carry = drain(carry, held)
        yield last


def train_naive_bayes_coo(
    doc_ptr: np.ndarray,
    feat_idx: np.ndarray,
    counts: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    n_features: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
    col_scale: Optional[np.ndarray] = None,
    pipeline: Optional[PipelineConfig] = None,
    pipeline_stats: Optional[PipelineStats] = None,
) -> NaiveBayesModel:
    """NB from the tokenizer's COO output (ops/tfidf.fit_tf_coo): the
    dense [N, D] matrix never exists — only the ~150 distinct buckets
    per doc cross the host->device link (13x fewer bytes at the
    20-newsgroups shape), and the class-feature stats come from one
    device scatter-add. Numerically equivalent to train_naive_bayes on
    the materialized matrix: the per-class sum is the same additions in
    a different association order, both accumulating f32 (tests pin
    near-identity; ulp-level reduction-order differences are possible).

    Uploads narrow where lossless: feature ids as uint16 when D fits,
    class ids as uint8 when C fits, counts as uint16 when all counts do
    (per-doc term frequencies overwhelmingly fit).

    ``pipeline``: when the entry stream is large enough, upload and
    scatter-add run as an overlapped fixed-size chunk stream (see
    train_naive_bayes_coo_stream, which additionally overlaps the
    tokenizer itself when fed from a chunked corpus).
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    y = np.asarray(y, np.int32)
    cls_per_entry = np.repeat(y, np.diff(np.asarray(doc_ptr)))
    feat_idx = np.asarray(feat_idx)
    counts = np.asarray(counts, np.float32)

    cfg = pipeline or PipelineConfig.from_env()
    if cfg.enabled_for(len(feat_idx)):
        return train_naive_bayes_coo_stream(
            iter([(cls_per_entry, feat_idx, counts)]), y, n_classes,
            n_features, smoothing=smoothing, mesh=mesh, col_scale=col_scale,
            pipeline=cfg, pipeline_stats=pipeline_stats,
        )

    # lossless narrow uploads (widened on device by _nb_stats_coo)
    cls_up, feat_up, cnt_up = _narrow_coo_chunk(
        cls_per_entry, feat_idx, counts, n_classes, n_features)

    cp = pad_rows(cls_up, n_dev)
    fp = pad_rows(feat_up, n_dev)
    wp = pad_rows(cnt_up, n_dev)      # pad counts are 0: contribute nothing
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    cp = fast_put(cp, shard1)
    fp = fast_put(fp, shard1)
    wp = fast_put(wp, shard1)
    feat = np.asarray(jax.device_get(
        _nb_stats_coo(cp, fp, wp, n_classes, n_features)))
    return _nb_model_from_stats(feat, y, n_classes, smoothing, col_scale)


def _nb_model_from_stats(feat, y, n_classes, smoothing, col_scale):
    if col_scale is not None:
        feat = feat * np.asarray(col_scale, np.float32)
    class_counts = np.bincount(y, minlength=n_classes).astype(np.float64)
    return nb_model_from_counts(feat, class_counts, n_classes, smoothing,
                                keep_counts=col_scale is None)


def train_naive_bayes_coo_stream(
    entry_blocks: Iterable[tuple],
    y: np.ndarray,
    n_classes: int,
    n_features: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
    col_scale=None,
    pipeline: Optional[PipelineConfig] = None,
    pipeline_stats: Optional[PipelineStats] = None,
) -> NaiveBayesModel:
    """NB from a STREAM of COO entry blocks — the fully overlapped text
    path: tokenizer workers (prefetch over doc chunks) feed ragged
    (cls, feat, counts) blocks, which are rebatched into fixed-size
    entry chunks, uploaded narrow, and scatter-added into the running
    device stats while the next chunk tokenizes. Bit-identical to
    train_naive_bayes_coo on the concatenated stream (same integer
    additions, different association order — exact in f32).

    ``col_scale`` may be a ZERO-ARG CALLABLE evaluated after the stream
    is exhausted: TF-IDF's idf only exists once the last chunk's
    document frequencies are counted.
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    y = np.asarray(y, np.int32)
    cfg = pipeline or PipelineConfig.from_env()
    step = max(n_dev, -(-cfg.chunk_rows // n_dev) * n_dev)
    shard1 = NamedSharding(mesh, P(DATA_AXIS))

    def upload(chunk):
        cls_e, feat_e, cnt_e = _narrow_coo_chunk(
            np.asarray(chunk[0]), np.asarray(chunk[1]),
            np.asarray(chunk[2], np.float32), n_classes, n_features)
        return (fast_put(pad_rows(cls_e, step), shard1),
                fast_put(pad_rows(feat_e, step), shard1),
                fast_put(pad_rows(cnt_e, step), shard1))

    acc = jnp.zeros((n_classes * n_features,), jnp.float32)

    def consume(dev):
        nonlocal acc
        acc, ready = _nb_stats_coo_acc(acc, *dev, n_features)
        return ready

    run_pipeline(rebatch_entries(entry_blocks, step), upload, consume,
                 depth=cfg.depth, stats=pipeline_stats)
    feat = np.asarray(jax.device_get(acc)).reshape(n_classes, n_features)
    if callable(col_scale):
        col_scale = col_scale()
    return _nb_model_from_stats(feat, y, n_classes, smoothing, col_scale)


# ---------------------------------------------------------------------------
# Logistic regression (multinomial softmax, L2, L-BFGS)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LogisticRegressionModel:
    weights: np.ndarray  # [D, C]
    intercept: np.ndarray  # [C]
    n_classes: int

    def predict_logits(self, x: np.ndarray) -> np.ndarray:
        return x @ self.weights + self.intercept

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        z = self.predict_logits(x)
        z = z - z.max(axis=-1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=-1, keepdims=True)


def lr_sgd_steps(model: LogisticRegressionModel, x: np.ndarray,
                 y: np.ndarray, *, reg: float = 0.0, lr: float = 0.05,
                 epochs: int = 5) -> Optional[LogisticRegressionModel]:
    """Online SGD on a COPY of an LR model: a few full-batch softmax
    cross-entropy gradient steps over the NEW examples only — the
    streaming fold-in update (workflow/online.py). Host numpy on
    purpose: an increment is a handful of examples, and warm serving
    weights only need a nudge toward them, not an L-BFGS re-solve.
    Returns None on shape mismatch (feature count changed: retrain)."""
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int64)
    w = np.array(model.weights, np.float32, copy=True)
    b = np.array(model.intercept, np.float32, copy=True)
    if x.ndim != 2 or x.shape[1] != w.shape[0] or len(x) != len(y) \
            or not len(x):
        return None
    onehot = np.zeros((len(y), model.n_classes), np.float32)
    onehot[np.arange(len(y)), y] = 1.0
    for _ in range(max(1, int(epochs))):
        z = x @ w + b
        z -= z.max(axis=1, keepdims=True)
        e = np.exp(z)
        p = e / e.sum(axis=1, keepdims=True)
        g = (p - onehot) / len(y)
        w -= lr * (x.T @ g + reg * w)
        b -= lr * g.sum(axis=0)
    return LogisticRegressionModel(weights=w, intercept=b,
                                   n_classes=model.n_classes)


@functools.partial(jax.jit, static_argnames=("n_classes",),
                   donate_argnums=())
def _lr_fit(xp, yp, maskp, n, reg, tol, max_iters, n_classes: int):
    """The ENTIRE L-BFGS optimization in one jit (lax.while_loop with
    the convergence test on-device). Module-level so the compiled
    executable is REUSED across train calls at the same shapes — a
    per-call closure would retrace+recompile every `pio train`, and a
    host-side step loop would pay a dispatch+readback round trip per
    iteration (~1s/iter through a remote-PJRT tunnel, 1000x the actual
    step cost at template shapes)."""
    import optax

    # narrow wire dtypes (uint8 / lossless bf16) widen back to f32
    # BEFORE any math: results are bit-identical to an f32 upload
    xp = xp.astype(jnp.float32)

    d = xp.shape[1]

    def loss_fn(params):
        w, b = params
        logits = xp @ w + b  # [Np, C] row-sharded
        logp = jax.nn.log_softmax(logits)
        # one-hot contraction, NOT take_along_axis: a per-row gather runs
        # at the TPU gather unit's fixed ~420M rows/s (BASELINE.md
        # roofline) — 6x the cost of this elementwise mask at bench shape.
        onehot = jax.nn.one_hot(yp, n_classes, dtype=logp.dtype)
        nll = -(logp * onehot).sum(axis=1)
        data = jnp.sum(nll * maskp) / n
        return data + 0.5 * reg * jnp.sum(w * w)

    # Backtracking linesearch instead of the default zoom: zoom's
    # while_loop lowers to ~1.7s/step at 2M-example shape (hundreds of
    # serialized loss evals); backtracking converges the template
    # configurations identically at ~3ms/step.
    opt = optax.lbfgs(linesearch=optax.scale_by_backtracking_linesearch(
        max_backtracking_steps=20, store_grad=True))
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        it, params, state, prev, _ = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        # optax.tree.norm is the 0.2.4+ spelling; older optax (this
        # container ships 0.2.3) has the same function as
        # tree_utils.tree_l2_norm
        tree_ns = getattr(optax, "tree", None)
        gnorm = (tree_ns.norm(grad) if tree_ns is not None
                 else optax.tree_utils.tree_l2_norm(grad))
        done = (jnp.abs(prev - value)
                < tol * jnp.maximum(1.0, jnp.abs(prev))) & (gnorm < 1e-4)
        return it + 1, params, state, value, done

    def cond(carry):
        it, _, _, _, done = carry
        return (it < max_iters) & ~done

    params = (jnp.zeros((d, n_classes)), jnp.zeros((n_classes,)))
    carry = (jnp.int32(0), params, opt.init(params), jnp.float32(jnp.inf),
             jnp.bool_(False))
    carry = jax.lax.while_loop(cond, step, carry)
    return carry[1]


@functools.lru_cache(maxsize=8)
def _cached_concat_widen(n_chunks: int, sharding):
    """jit'd on-device assembly of the full row-sharded f32 matrix from
    the streamed chunks (module-cached so warm trains reuse the
    executable). Chunks are donated — XLA reclaims their HBM into the
    result instead of holding both."""
    def cat(*chunks):
        return jnp.concatenate([c.astype(jnp.float32) for c in chunks],
                               axis=0)

    # CPU can't alias into a concatenate — donating there only emits a
    # "donated buffers were not usable" warning per call
    donate = (tuple(range(n_chunks))
              if jax.default_backend() != "cpu" else ())
    return jax.jit(cat, out_shardings=sharding, donate_argnums=donate)


def _stream_lr_upload(x, mesh, on_tpu, cfg: PipelineConfig,
                      stats: Optional[PipelineStats]):
    """Overlapped narrow-cast + upload of the LR feature matrix: workers
    cast chunk N+1 to its narrowest lossless wire dtype while chunk N
    uploads; the sharded full array is then assembled on device. Row
    content (incl. zero pad rows) matches pad_rows(x, n_dev) exactly."""
    n_dev = int(np.prod(list(mesh.shape.values())))
    n = x.shape[0]
    step = max(n_dev, -(-min(cfg.chunk_rows, n) // n_dev) * n_dev)
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))

    def featurize(rng):
        s, e = rng
        xc = _narrow_wire(x[s:e], on_tpu)
        # only the LAST chunk can be non-divisible: pad it like the
        # single-shot global pad (same total row count, same zeros)
        return pad_rows(xc, n_dev) if (e - s) % n_dev else xc

    dev_chunks = []

    def consume(dev):
        dev_chunks.append(dev)
        return dev

    chunks = prefetch(chunk_ranges(n, step), featurize,
                      workers=cfg.workers, lookahead=cfg.depth + 1,
                      stats=stats)
    run_pipeline(chunks, lambda hc: fast_put(hc, shard2), consume,
                 depth=cfg.depth, stats=stats)
    if len(dev_chunks) == 1:
        return dev_chunks[0]
    return _cached_concat_widen(len(dev_chunks), shard2)(*dev_chunks)


def train_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    reg: float = 0.0,
    max_iters: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
    pipeline: Optional[PipelineConfig] = None,
    pipeline_stats: Optional[PipelineStats] = None,
) -> LogisticRegressionModel:
    """Full-batch multinomial LR via optax L-BFGS; data row-sharded over
    the mesh, gradient psum inserted by XLA.

    ``pipeline``: L-BFGS needs the whole matrix resident, so the stream
    cannot reduce chunks away like NB — instead the narrowing cast and
    the upload overlap per chunk, and the full sharded [Np, D] array is
    assembled ON DEVICE from the uploaded chunks (one concatenate; the
    chunks are donated into it). The assembled array is bit-identical
    to the single-shot upload, so the fitted model is too.
    """
    mesh = mesh or default_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n = x.shape[0]
    on_tpu = mesh.devices.flat[0].platform == "tpu"
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    cfg = pipeline or PipelineConfig.from_env()
    if cfg.enabled_for(n):
        xp = _stream_lr_upload(x, mesh, on_tpu, cfg, pipeline_stats)
        yp = fast_put(pad_rows(y, n_dev), shard1)
        maskp = fast_put(pad_rows(np.ones(n, np.float32), n_dev), shard1)
    else:
        # Lossless narrow wire (same gate as train_naive_bayes); _lr_fit
        # widens back to f32 on device FIRST, so the optimization math
        # and its results are bit-identical to an f32 upload.
        x = _narrow_wire(x, on_tpu)
        mask = pad_rows(np.ones(n, np.float32), n_dev)
        xp = fast_put(pad_rows(x, n_dev), shard2)
        yp = fast_put(pad_rows(y, n_dev), shard1)
        maskp = fast_put(mask, shard1)

    params = _lr_fit(xp, yp, maskp, jnp.float32(n), jnp.float32(reg),
                     jnp.float32(tol), jnp.int32(max_iters), n_classes)
    w, b = jax.device_get(params)
    return LogisticRegressionModel(
        weights=np.asarray(w, np.float32),
        intercept=np.asarray(b, np.float32),
        n_classes=n_classes,
    )


# ---------------------------------------------------------------------------
# partition-local (process-sharded) entry points — SparkNet-style
# synchronous data parallelism (arxiv 1511.06051) over the gang mesh
# ---------------------------------------------------------------------------


def _assemble_process_shards(x: np.ndarray, y: np.ndarray,
                             mesh: Mesh):
    """Assemble each gang process's LOCAL example block into global
    row-sharded arrays: rows are padded (mask 0) to the gang-wide
    per-device maximum so every process compiles the identical
    program, then stitched with ``make_array_from_process_local_data``.
    Row ownership is irrelevant — both consumers reduce with psum'd
    sums that zero-mask rows contribute nothing to. Returns
    ``(xp, yp, maskp, n_global)`` with ``n_global`` the gang-wide real
    example count (the loss normalizer).

    No wire narrowing here on purpose: the narrow dtype is a function
    of the LOCAL block, and per-process dtype disagreement would
    compile divergent programs across the gang.
    """
    from jax.experimental import multihost_utils

    n_proc = jax.process_count()
    n_dev = int(np.prod(list(mesh.shape.values())))
    if n_dev % n_proc:
        raise ValueError(
            f"{n_dev} devices do not divide {n_proc} processes")
    local_devs = n_dev // n_proc
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.int32)
    n_local = x.shape[0]

    def agather(v):
        return np.asarray(
            multihost_utils.process_allgather(
                np.asarray(v, np.int32))).reshape(-1)

    per_dev = int(agather(-(-max(n_local, 1) // local_devs)).max())
    n_global = int(agather(n_local).sum())
    rows_local = per_dev * local_devs

    def pad_block(a):
        out = np.zeros((rows_local,) + a.shape[1:], a.dtype)
        out[:n_local] = a
        return out

    xl = pad_block(x)
    yl = pad_block(y)
    ml = pad_block(np.ones(n_local, np.float32))
    shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
    shard1 = NamedSharding(mesh, P(DATA_AXIS))

    def to_global(a, sh):
        if n_proc == 1:
            return fast_put(a, sh)
        return jax.make_array_from_process_local_data(
            sh, a, (a.shape[0] * n_proc,) + a.shape[1:])

    return (to_global(xl, shard2), to_global(yl, shard1),
            to_global(ml, shard1), n_global)


def train_naive_bayes_process_local(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    smoothing: float = 1.0,
    mesh: Optional[Mesh] = None,
) -> NaiveBayesModel:
    """NB where each gang process holds only ITS event-log partitions'
    examples (workflow/train_feed.py). Sufficient statistics are pure
    sums, so the psum XLA inserts for the row-sharded one-hot matmul
    IS the cross-partition reduction — the result is exactly the
    single-process model over the union (integer counts sum exactly in
    f32). ``n_classes`` must be the gang-agreed GLOBAL class count
    (the label vocabulary is allgathered by the feed orchestrator)."""
    mesh = mesh or default_mesh()
    if jax.process_count() == 1:
        return train_naive_bayes(x, y, n_classes, smoothing=smoothing,
                                 mesh=mesh)
    xp, yp, wp, _n = _assemble_process_shards(x, y, mesh)
    feat, counts = jax.device_get(_nb_stats(xp, yp, wp, n_classes))
    return nb_model_from_counts(feat, counts, n_classes, smoothing)


def train_logistic_regression_process_local(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    reg: float = 0.0,
    max_iters: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
) -> LogisticRegressionModel:
    """LR over partition-local example blocks: the SAME jitted L-BFGS
    (:func:`_lr_fit`) the single-process path runs — its loss/grad
    sums are row-sharded psums, so feeding each process its own
    partitions' rows (mask-padded to a common shape) yields
    synchronous data-parallel training with gradients all-reduced
    every step (SparkNet, arxiv 1511.06051). The loss normalizer is
    the gang-wide example count."""
    mesh = mesh or default_mesh()
    if jax.process_count() == 1:
        return train_logistic_regression(
            x, y, n_classes, reg=reg, max_iters=max_iters, tol=tol,
            mesh=mesh)
    xp, yp, maskp, n_global = _assemble_process_shards(x, y, mesh)
    params = _lr_fit(xp, yp, maskp, jnp.float32(n_global),
                     jnp.float32(reg), jnp.float32(tol),
                     jnp.int32(max_iters), n_classes)
    w, b = jax.device_get(params)
    return LogisticRegressionModel(
        weights=np.asarray(w, np.float32),
        intercept=np.asarray(b, np.float32),
        n_classes=n_classes,
    )
