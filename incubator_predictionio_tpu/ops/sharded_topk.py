"""Sharded serving: top-k over catalogs bigger than one chip's HBM.

Reference: core/.../controller/PAlgorithm.scala — batchPredict (models that
stay distributed at serve time and are queried without collecting to one
node; the MLlib ALX scenario SURVEY.md §7 "hard parts" names explicitly).

TPU-native redesign: the item-factor matrix lives sharded over EVERY device
of the serving mesh (dim 0 split across all mesh axes). A query computes
per-shard local scores and a per-shard local top-k, then all_gathers only
the k-candidate (score, global-index) pairs — never a full score row — and
merges them with a two-key lexicographic sort that reproduces single-device
``lax.top_k`` semantics bit-for-bit (ties break toward the lowest global
index, exactly as ``lax.top_k`` does). Per-query collective traffic is
O(shards * k * 8 bytes), independent of catalog size, so it rides ICI
comfortably at serving rates.

Bit-identity with the single-device kernels in ops/topk.py is a tested
invariant (tests/test_sharded_serving.py): sharding splits rows, never the
rank-reduction axis, and the merge preserves top_k's selection + tie order.
The single-query (matvec) and similarity paths are bitwise identical to
their unsharded counterparts. The batched path returns identical indices
in identical order with scores equal to ≤2 ULP: gemm libraries block the
reduction by OUTPUT shape, so even the unsharded kernel produces slightly
different bits for a [b, N] vs [b, N/8] product — measured, not assumed
(same holds for MXU tilings on TPU). Matvec lowers per-row and is
shape-independent, which is why the serving hot path stays exact.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..common.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import pad_rows
from .topk import bucket_k, pad_batch_pow2


def _mesh_axes(mesh: Mesh) -> tuple[str, ...]:
    return tuple(mesh.axis_names)


@dataclasses.dataclass
class ShardedCatalog:
    """Item-factor matrix resident sharded over all devices of a mesh.

    ``dev`` is [Np, rank] with dim 0 split over every mesh axis; rows
    ``n_items..Np-1`` are zero padding (masked to -inf inside the kernels
    so they can never displace a real item).
    """

    dev: object
    n_items: int
    mesh: Mesh

    @property
    def rank(self) -> int:
        return self.dev.shape[1]

    @property
    def padded_rows(self) -> int:
        return self.dev.shape[0]

    @property
    def n_shards(self) -> int:
        return int(self.mesh.size)


def put_sharded_catalog(item_factors, mesh: Mesh) -> ShardedCatalog:
    """Host factors → device catalog sharded over all mesh axes on dim 0."""
    x = np.asarray(item_factors, np.float32)
    shards = int(mesh.size)
    padded = pad_rows(x, shards)
    sharding = NamedSharding(mesh, P(_mesh_axes(mesh), None))
    return ShardedCatalog(jax.device_put(padded, sharding), x.shape[0], mesh)


# -- sharding decision -----------------------------------------------------


def _serving_shard_threshold_bytes() -> int:
    """Catalog size beyond which "auto" shards serving: an explicit
    PIO_SHARDED_SERVING_BYTES wins (malformed → warn + device default);
    otherwise 1/4 of the device's reported memory — factors compete with
    the training slabs and per-query intermediates for HBM. Tunnels that
    report no memory stats assume the fleet-minimum 8 GiB TPU."""
    from ..common import envknobs

    raw = envknobs.env_str("PIO_SHARDED_SERVING_BYTES", "")
    if raw:
        explicit = envknobs.env_int("PIO_SHARDED_SERVING_BYTES", 0,
                                    float_ok=True)
        if explicit > 0:
            return explicit
        import warnings

        warnings.warn(
            f"PIO_SHARDED_SERVING_BYTES={raw!r} is not a positive "
            "number; using the device-derived default", stacklevel=2)
    limit = 0
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit <= 0 and dev.platform == "tpu":
            limit = 8 * 1024 ** 3
    except Exception:
        pass
    if limit <= 0:
        limit = 4 * 1024 ** 3
    return limit // 4


def validate_serving_mode(mode: str) -> str:
    """Fail fast on a bad "shardedServing" value — called at the TOP of
    train so a typo dies before the expensive ALS run, not after it."""
    if mode not in ("auto", "always", "never"):
        raise ValueError(
            f"shardedServing={mode!r}: expected auto|always|never")
    return mode


def should_shard_serving(
    n_items: int, rank: int, mesh: Optional[Mesh], mode: str = "auto"
) -> bool:
    """Deploy-time policy: shard item factors over the mesh?

    mode: "never" | "always" | "auto" (auto → shard when the f32 factor
    matrix exceeds the per-chip budget). Engine.json spelling:
    "shardedServing". A 1-device mesh never shards (nothing to split)."""
    validate_serving_mode(mode)
    if mesh is None or mode == "never" or int(mesh.size) <= 1:
        return False
    if mode == "always":
        return True
    return n_items * rank * 4 > _serving_shard_threshold_bytes()


def serving_mesh_for(ctx, n_items: int, rank: int, mode: str):
    """The deploy-time sharding decision every ALS-family algorithm
    shares (train + restore_model): the ctx mesh when policy says shard,
    else None (single-chip serving)."""
    mesh = ctx.get_mesh() if ctx is not None else None
    return mesh if should_shard_serving(n_items, rank, mesh, mode) else None


# -- kernels ---------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _sharded_topk_fn(mesh: Mesh, k: int, has_exclude: bool):
    """Compile-cached sharded scorer (user·item affinity over a batch of
    query rows; similarity queries fold into a single row upstream).
    Cached per (mesh, bucketed-k, exclude?) so serving reuses
    executables across queries; jit handles shape specialisation below."""
    axes = _mesh_axes(mesh)
    shards = int(mesh.size)
    axis_sizes = [mesh.shape[a] for a in axes]
    item_spec = P(axes, None)
    row_spec = P(axes)

    def shard_fn(qv, local_items, local_excl, n_items):
        nl = local_items.shape[0]
        sid = jnp.int32(0)
        for a, _sz in zip(axes, axis_sizes):
            sid = sid * _sz + jax.lax.axis_index(a)
        rows = sid * nl + jnp.arange(nl, dtype=jnp.int32)
        if qv.shape[0] == 1:
            # single query: the same row-invariant mul+reduce the
            # single-device _topk_scores uses → bitwise-identical scores
            scores = (local_items * qv[0][None, :]).sum(axis=1)[None, :]
        else:
            scores = qv @ local_items.T  # [b, nl]
        dead = rows >= n_items
        if has_exclude:
            dead = dead | local_excl
        scores = jnp.where(dead[None, :], -jnp.inf, scores)
        kl = min(k, nl)
        s, li = jax.lax.top_k(scores, kl)  # [b, kl] local candidates
        gi = jnp.take(rows, li)
        gs = jax.lax.all_gather(s, axes)
        gg = jax.lax.all_gather(gi, axes)
        gs = gs.reshape((shards,) + s.shape)
        gg = gg.reshape((shards,) + gi.shape)
        b = s.shape[0]
        cand_s = jnp.moveaxis(gs, 0, 1).reshape(b, shards * kl)
        cand_i = jnp.moveaxis(gg, 0, 1).reshape(b, shards * kl)
        # two-key sort: score descending, global index ascending — the
        # exact tie order lax.top_k produces on an unsharded score row
        neg, idx = jax.lax.sort((-cand_s, cand_i), dimension=1, num_keys=2)
        kk = min(k, shards * kl)
        return -neg[:, :kk], idx[:, :kk]

    excl_spec = row_spec if has_exclude else P()

    @jax.jit
    def run(qv, items, excl, n_items):
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), item_spec, excl_spec, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )(qv, items, excl, n_items)

    return run


def _put_exclude(exclude, cat: ShardedCatalog):
    mask = pad_rows(np.asarray(exclude, bool), cat.n_shards, fill=True)
    return jax.device_put(
        mask, NamedSharding(cat.mesh, P(_mesh_axes(cat.mesh))))


def sharded_top_k_items(user_vec, cat: ShardedCatalog, k: int, exclude=None):
    """Sharded analog of ops.topk.top_k_items — (scores[k], idx[k]) host."""
    k = min(int(k), cat.n_items)
    kp = bucket_k(k, cat.n_items)
    qv = np.asarray(user_vec, np.float32)[None, :]
    fn = _sharded_topk_fn(cat.mesh, kp, exclude is not None)
    excl = _put_exclude(exclude, cat) if exclude is not None else np.zeros(0, bool)
    s, i = jax.device_get(
        fn(qv, cat.dev, excl, np.int32(cat.n_items)))
    return s[0, :k], i[0, :k]


def sharded_batch_top_k(user_vecs, cat: ShardedCatalog, k: int):
    """Sharded analog of ops.topk.batch_top_k (same batch pow2 padding)."""
    user_vecs = np.asarray(user_vecs, np.float32)
    k = min(int(k), cat.n_items)
    b = user_vecs.shape[0]
    user_vecs = pad_batch_pow2(user_vecs)
    kp = bucket_k(k, cat.n_items)
    fn = _sharded_topk_fn(cat.mesh, kp, False)
    s, i = jax.device_get(
        fn(user_vecs, cat.dev, np.zeros(0, bool), np.int32(cat.n_items)))
    return s[:b, :k], i[:b, :k]


def sharded_similar_items(query_vecs, cat: ShardedCatalog, k: int, exclude=None):
    """Sharded analog of ops.topk.similar_items — ``cat`` must hold
    ROW-NORMALIZED factors (ops.topk.normalize_rows), mirroring the
    single-device contract. The query fold makes this the single-query
    matvec path, so scores are bitwise identical to the unsharded kernel."""
    from .topk import normalize_rows

    qn = normalize_rows(np.atleast_2d(np.asarray(query_vecs, np.float32)))
    return sharded_top_k_items(qn.sum(axis=0), cat, k, exclude=exclude)
