"""Sharded serving: top-k over catalogs bigger than one chip's HBM.

Reference: core/.../controller/PAlgorithm.scala — batchPredict (models that
stay distributed at serve time and are queried without collecting to one
node; the MLlib ALX scenario SURVEY.md §7 "hard parts" names explicitly).

TPU-native redesign: the item-factor matrix lives sharded over EVERY device
of the serving mesh (dim 0 split across all mesh axes). A query computes
per-shard local scores and a per-shard local top-k, then all_gathers only
the k-candidate (score, global-index) pairs — never a full score row — and
merges them with a two-key lexicographic sort that reproduces single-device
``lax.top_k`` semantics bit-for-bit (ties break toward the lowest global
index, exactly as ``lax.top_k`` does). Per-query collective traffic is
O(shards * k * 8 bytes), independent of catalog size, so it rides ICI
comfortably at serving rates.

Bit-identity with the single-device kernels in ops/topk.py is a tested
invariant (tests/test_sharded_serving.py): sharding splits rows, never the
rank-reduction axis, and the merge preserves top_k's selection + tie order.
The single-query (matvec) and similarity paths are bitwise identical to
their unsharded counterparts. The batched path returns identical indices
in identical order with scores equal to ≤2 ULP: gemm libraries block the
reduction by OUTPUT shape, so even the unsharded kernel produces slightly
different bits for a [b, N] vs [b, N/8] product — measured, not assumed
(same holds for MXU tilings on TPU). Matvec lowers per-row and is
shape-independent, which is why the serving hot path stays exact.

Two shard layouts share that contract:

- MESH sharding (``ShardedCatalog``): catalogs beyond one chip's HBM,
  dim 0 split over every device of the serving mesh, candidates merged
  through an all_gather. One shard per device.
- HOST sharding (``HostShardedCatalog``): million-item catalogs on a
  SINGLE device. The catalog lives as one stacked [S, rows, rank] device
  array and a ``lax.scan`` walks the shard axis, so peak per-step memory
  is one shard's score row instead of the full [b, N] score matrix, and
  business-rule filters mask each shard BEFORE its partial top-k so
  filtered items never reach the merge. Armed by ``PIO_SERVE_SHARD_ITEMS``
  (rows per shard; 0 = off). The merge is the same two-key sort, so the
  bit-identity contract above carries over verbatim.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..common.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import pad_rows
from .topk import bucket_k, pad_batch_pow2


def _mesh_axes(mesh: Mesh) -> tuple[str, ...]:
    return tuple(mesh.axis_names)


@dataclasses.dataclass
class ShardedCatalog:
    """Item-factor matrix resident sharded over all devices of a mesh.

    ``dev`` is [Np, rank] with dim 0 split over every mesh axis; rows
    ``n_items..Np-1`` are zero padding (masked to -inf inside the kernels
    so they can never displace a real item).
    """

    dev: object
    n_items: int
    mesh: Mesh

    @property
    def rank(self) -> int:
        return self.dev.shape[1]

    @property
    def padded_rows(self) -> int:
        return self.dev.shape[0]

    @property
    def n_shards(self) -> int:
        return int(self.mesh.size)


def put_sharded_catalog(item_factors, mesh: Mesh) -> ShardedCatalog:
    """Host factors → device catalog sharded over all mesh axes on dim 0."""
    x = np.asarray(item_factors, np.float32)
    shards = int(mesh.size)
    padded = pad_rows(x, shards)
    sharding = NamedSharding(mesh, P(_mesh_axes(mesh), None))
    return ShardedCatalog(jax.device_put(padded, sharding), x.shape[0], mesh)


# -- sharding decision -----------------------------------------------------


def _serving_shard_threshold_bytes() -> int:
    """Catalog size beyond which "auto" shards serving: an explicit
    PIO_SHARDED_SERVING_BYTES wins (malformed → warn + device default);
    otherwise 1/4 of the device's reported memory — factors compete with
    the training slabs and per-query intermediates for HBM. Tunnels that
    report no memory stats assume the fleet-minimum 8 GiB TPU."""
    from ..common import envknobs

    raw = envknobs.env_str("PIO_SHARDED_SERVING_BYTES", "")
    if raw:
        explicit = envknobs.env_int("PIO_SHARDED_SERVING_BYTES", 0,
                                    float_ok=True)
        if explicit > 0:
            return explicit
        import warnings

        warnings.warn(
            f"PIO_SHARDED_SERVING_BYTES={raw!r} is not a positive "
            "number; using the device-derived default", stacklevel=2)
    limit = 0
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit <= 0 and dev.platform == "tpu":
            limit = 8 * 1024 ** 3
    except Exception:
        pass
    if limit <= 0:
        limit = 4 * 1024 ** 3
    return limit // 4


def validate_serving_mode(mode: str) -> str:
    """Fail fast on a bad "shardedServing" value — called at the TOP of
    train so a typo dies before the expensive ALS run, not after it."""
    if mode not in ("auto", "always", "never"):
        raise ValueError(
            f"shardedServing={mode!r}: expected auto|always|never")
    return mode


def should_shard_serving(
    n_items: int, rank: int, mesh: Optional[Mesh], mode: str = "auto"
) -> bool:
    """Deploy-time policy: shard item factors over the mesh?

    mode: "never" | "always" | "auto" (auto → shard when the f32 factor
    matrix exceeds the per-chip budget). Engine.json spelling:
    "shardedServing". A 1-device mesh never shards (nothing to split)."""
    validate_serving_mode(mode)
    if mesh is None or mode == "never" or int(mesh.size) <= 1:
        return False
    if mode == "always":
        return True
    return n_items * rank * 4 > _serving_shard_threshold_bytes()


def serving_mesh_for(ctx, n_items: int, rank: int, mode: str):
    """The deploy-time sharding decision every ALS-family algorithm
    shares (train + restore_model): the ctx mesh when policy says shard,
    else None (single-chip serving)."""
    mesh = ctx.get_mesh() if ctx is not None else None
    return mesh if should_shard_serving(n_items, rank, mesh, mode) else None


# -- kernels ---------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _sharded_topk_fn(mesh: Mesh, k: int, has_exclude: bool):
    """Compile-cached sharded scorer (user·item affinity over a batch of
    query rows; similarity queries fold into a single row upstream).
    Cached per (mesh, bucketed-k, exclude?) so serving reuses
    executables across queries; jit handles shape specialisation below."""
    axes = _mesh_axes(mesh)
    shards = int(mesh.size)
    axis_sizes = [mesh.shape[a] for a in axes]
    item_spec = P(axes, None)
    row_spec = P(axes)

    def shard_fn(qv, local_items, local_excl, n_items):
        nl = local_items.shape[0]
        sid = jnp.int32(0)
        for a, _sz in zip(axes, axis_sizes):
            sid = sid * _sz + jax.lax.axis_index(a)
        rows = sid * nl + jnp.arange(nl, dtype=jnp.int32)
        if qv.shape[0] == 1:
            # single query: the same row-invariant mul+reduce the
            # single-device _topk_scores uses → bitwise-identical scores
            scores = (local_items * qv[0][None, :]).sum(axis=1)[None, :]
        else:
            scores = qv @ local_items.T  # [b, nl]
        dead = rows >= n_items
        if has_exclude:
            dead = dead | local_excl
        scores = jnp.where(dead[None, :], -jnp.inf, scores)
        kl = min(k, nl)
        s, li = jax.lax.top_k(scores, kl)  # [b, kl] local candidates
        gi = jnp.take(rows, li)
        gs = jax.lax.all_gather(s, axes)
        gg = jax.lax.all_gather(gi, axes)
        gs = gs.reshape((shards,) + s.shape)
        gg = gg.reshape((shards,) + gi.shape)
        b = s.shape[0]
        cand_s = jnp.moveaxis(gs, 0, 1).reshape(b, shards * kl)
        cand_i = jnp.moveaxis(gg, 0, 1).reshape(b, shards * kl)
        # two-key sort: score descending, global index ascending — the
        # exact tie order lax.top_k produces on an unsharded score row
        neg, idx = jax.lax.sort((-cand_s, cand_i), dimension=1, num_keys=2)
        kk = min(k, shards * kl)
        return -neg[:, :kk], idx[:, :kk]

    excl_spec = row_spec if has_exclude else P()

    @jax.jit
    def run(qv, items, excl, n_items):
        return shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(), item_spec, excl_spec, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )(qv, items, excl, n_items)

    return run


def _put_exclude(exclude, cat: ShardedCatalog):
    mask = pad_rows(np.asarray(exclude, bool), cat.n_shards, fill=True)
    return jax.device_put(
        mask, NamedSharding(cat.mesh, P(_mesh_axes(cat.mesh))))


def sharded_top_k_items(user_vec, cat: ShardedCatalog, k: int, exclude=None):
    """Sharded analog of ops.topk.top_k_items — (scores[k], idx[k]) host."""
    k = min(int(k), cat.n_items)
    kp = bucket_k(k, cat.n_items)
    qv = np.asarray(user_vec, np.float32)[None, :]
    fn = _sharded_topk_fn(cat.mesh, kp, exclude is not None)
    excl = _put_exclude(exclude, cat) if exclude is not None else np.zeros(0, bool)
    s, i = jax.device_get(
        fn(qv, cat.dev, excl, np.int32(cat.n_items)))
    return s[0, :k], i[0, :k]


def sharded_batch_top_k(user_vecs, cat: ShardedCatalog, k: int):
    """Sharded analog of ops.topk.batch_top_k (same batch pow2 padding)."""
    user_vecs = np.asarray(user_vecs, np.float32)
    k = min(int(k), cat.n_items)
    b = user_vecs.shape[0]
    user_vecs = pad_batch_pow2(user_vecs)
    kp = bucket_k(k, cat.n_items)
    fn = _sharded_topk_fn(cat.mesh, kp, False)
    s, i = jax.device_get(
        fn(user_vecs, cat.dev, np.zeros(0, bool), np.int32(cat.n_items)))
    return s[:b, :k], i[:b, :k]


def sharded_similar_items(query_vecs, cat: ShardedCatalog, k: int, exclude=None):
    """Sharded analog of ops.topk.similar_items — ``cat`` must hold
    ROW-NORMALIZED factors (ops.topk.normalize_rows), mirroring the
    single-device contract. The query fold makes this the single-query
    matvec path, so scores are bitwise identical to the unsharded kernel."""
    from .topk import normalize_rows

    qn = normalize_rows(np.atleast_2d(np.asarray(query_vecs, np.float32)))
    return sharded_top_k_items(qn.sum(axis=0), cat, k, exclude=exclude)


# -- host sharding: million-item catalogs on ONE device --------------------


def env_serve_shard_items() -> int:
    """Rows per host shard (PIO_SERVE_SHARD_ITEMS). 0 (the default)
    disables host sharding entirely — serving is then bit-identical to,
    and literally the same code path as, the pre-sharding engine."""
    from ..common import envknobs

    return envknobs.env_int("PIO_SERVE_SHARD_ITEMS", 0, lo=0,
                            float_ok=True, warn=True)


@dataclasses.dataclass
class HostShardedCatalog:
    """Item factors stacked [S, rows_per_shard, rank] on ONE device.

    Rows ``n_items..S*rows_per_shard-1`` (the last shard's tail) are zero
    padding; the kernels mask them to -inf so they can never displace a
    real item. Unlike the mesh ``ShardedCatalog`` the shard count is a
    capacity choice (``PIO_SERVE_SHARD_ITEMS``), not the device count:
    a ``lax.scan`` over the shard axis bounds peak score-row memory at
    one shard regardless of catalog size."""

    dev: object
    n_items: int

    @property
    def rank(self) -> int:
        return self.dev.shape[2]

    @property
    def rows_per_shard(self) -> int:
        return self.dev.shape[1]

    @property
    def n_shards(self) -> int:
        return self.dev.shape[0]


def _stack_shards(x: np.ndarray, rows_per_shard: int, fill=0):
    """[N, ...] → [S, rows_per_shard, ...] with the tail padded by
    ``fill``."""
    n = x.shape[0]
    shards = max(1, -(-n // rows_per_shard))
    pad = shards * rows_per_shard - n
    if pad:
        x = np.concatenate(
            [x, np.full((pad,) + x.shape[1:], fill, x.dtype)], axis=0)
    return x.reshape((shards, rows_per_shard) + x.shape[1:])


def put_host_sharded_catalog(item_factors,
                             rows_per_shard: int) -> HostShardedCatalog:
    """Host factors → single-device catalog stacked on a shard axis."""
    x = np.asarray(item_factors, np.float32)
    rows_per_shard = min(max(1, int(rows_per_shard)), max(1, x.shape[0]))
    stacked = _stack_shards(x, rows_per_shard)
    return HostShardedCatalog(jax.device_put(stacked), x.shape[0])


@functools.lru_cache(maxsize=None)
def _host_topk_fn(k: int, has_exclude: bool):
    """Compile-cached host-sharded scorer: scan the shard axis, per-shard
    mask (padding + business-rule filter) → partial top-k → exact global
    merge. Single-query rows use the row-invariant mul+reduce, so scores
    are bitwise identical to ops.topk._topk_scores; the batched rows use
    the same gemm contract as the mesh path (identical indices, scores
    within gemm-blocking ULPs)."""

    @jax.jit
    def run(qv, items, excl, n_items):
        shards, nl, _rank = items.shape
        kl = min(k, nl)

        def body(carry, xs):
            local_items, local_excl, b0 = xs
            rows = b0 + jnp.arange(nl, dtype=jnp.int32)
            if qv.shape[0] == 1:
                scores = (local_items * qv[0][None, :]).sum(axis=1)[None, :]
            else:
                scores = qv @ local_items.T  # [b, nl]
            dead = rows >= n_items
            if has_exclude:
                dead = dead | local_excl
            scores = jnp.where(dead[None, :], -jnp.inf, scores)
            s, li = jax.lax.top_k(scores, kl)  # [b, kl]
            return carry, (s, b0 + li)

        b0s = jnp.arange(shards, dtype=jnp.int32) * nl
        _, (ss, gg) = jax.lax.scan(body, 0, (items, excl, b0s))
        b = qv.shape[0]
        cand_s = jnp.moveaxis(ss, 0, 1).reshape(b, shards * kl)
        cand_i = jnp.moveaxis(gg, 0, 1).reshape(b, shards * kl)
        # same two-key merge as the mesh kernel: score descending, global
        # index ascending — lax.top_k's exact selection + tie order
        neg, idx = jax.lax.sort((-cand_s, cand_i), dimension=1, num_keys=2)
        kk = min(k, shards * kl)
        return -neg[:, :kk], idx[:, :kk]

    return run


def _stack_exclude(exclude, cat: HostShardedCatalog):
    mask = np.asarray(exclude, bool)
    return _stack_shards(mask, cat.rows_per_shard, fill=True)


def host_sharded_top_k_items(user_vec, cat: HostShardedCatalog, k: int,
                             exclude=None):
    """Host-sharded analog of ops.topk.top_k_items — (scores[k], idx[k])
    host numpy, bitwise identical to the unsharded kernel."""
    k = min(int(k), cat.n_items)
    kp = bucket_k(k, cat.n_items)
    qv = np.asarray(user_vec, np.float32)[None, :]
    fn = _host_topk_fn(kp, exclude is not None)
    excl = (_stack_exclude(exclude, cat) if exclude is not None
            else np.zeros((cat.n_shards, 1), bool))
    s, i = jax.device_get(fn(qv, cat.dev, excl, np.int32(cat.n_items)))
    return s[0, :k], i[0, :k]


def host_sharded_batch_top_k(user_vecs, cat: HostShardedCatalog, k: int):
    """Host-sharded analog of ops.topk.batch_top_k (same batch pow2
    padding), for the micro-batch window: one scanned dispatch scores the
    WHOLE coalesced batch against every shard."""
    user_vecs = np.asarray(user_vecs, np.float32)
    k = min(int(k), cat.n_items)
    b = user_vecs.shape[0]
    user_vecs = pad_batch_pow2(user_vecs)
    kp = bucket_k(k, cat.n_items)
    fn = _host_topk_fn(kp, False)
    s, i = jax.device_get(
        fn(user_vecs, cat.dev, np.zeros((cat.n_shards, 1), bool),
           np.int32(cat.n_items)))
    return s[:b, :k], i[:b, :k]


def host_sharded_similar_items(query_vecs, cat: HostShardedCatalog, k: int,
                               exclude=None):
    """Host-sharded analog of ops.topk.similar_items — ``cat`` must hold
    ROW-NORMALIZED factors; the query fold keeps this on the bitwise-
    exact single-query path."""
    from .topk import normalize_rows

    qn = normalize_rows(np.atleast_2d(np.asarray(query_vecs, np.float32)))
    return host_sharded_top_k_items(qn.sum(axis=0), cat, k, exclude=exclude)


# -- host sharding for the universal recommender's indicator scorer -------


@dataclasses.dataclass
class HostShardedIndicators:
    """One event type's correlator table stacked [S, rows_per_shard, K]
    on one device. Padding rows hold idx=-1 (the "no correlator" value),
    so their gathered membership — and score contribution — is zero; the
    kernel additionally masks them to -inf before the partial top-k."""

    idx: object    # int32 [S, nl, K]
    score: object  # float32 [S, nl, K]

    @property
    def rows_per_shard(self) -> int:
        return self.idx.shape[1]

    @property
    def n_shards(self) -> int:
        return self.idx.shape[0]


def put_host_sharded_indicators(indicators,
                                rows_per_shard: int) -> HostShardedIndicators:
    """ops.llr.Indicators → stacked single-device shard layout."""
    idx = np.asarray(indicators.idx, np.int32)
    score = np.asarray(indicators.score, np.float32)
    rows_per_shard = min(max(1, int(rows_per_shard)), max(1, idx.shape[0]))
    return HostShardedIndicators(
        jax.device_put(_stack_shards(idx, rows_per_shard, fill=-1)),
        jax.device_put(_stack_shards(score, rows_per_shard)))


@functools.lru_cache(maxsize=None)
def _host_ur_topk_fn(k: int, n_types: int):
    """Host-sharded twin of ops.llr.score_user: the einsum reduction runs
    over the correlator axis PER ROW, so sharding the item rows leaves
    every row's arithmetic — gather, einsum, boost, exclude — bitwise
    intact; only the top-k selection is split and exactly re-merged."""

    @jax.jit
    def run(idxs, scores, membs, boosts, item_boost, exclude, n_items):
        shards, nl = idxs[0].shape[0], idxs[0].shape[1]
        kl = min(k, nl)

        def body(carry, xs):
            loc_idx, loc_score, ib, ex, b0 = xs
            rows = b0 + jnp.arange(nl, dtype=jnp.int32)
            total = jnp.zeros((nl,), jnp.float32)
            for t in range(n_types):
                m = jnp.where(loc_idx[t] >= 0,
                              membs[t][jnp.maximum(loc_idx[t], 0)], 0.0)
                total = total + jnp.einsum(
                    "ik,ik->i", loc_score[t], m) * boosts[t]
            total = total * ib
            total = jnp.where((rows >= n_items) | ex, -jnp.inf, total)
            s, li = jax.lax.top_k(total[None, :], kl)
            return carry, (s[0], b0 + li[0])

        b0s = jnp.arange(shards, dtype=jnp.int32) * nl
        _, (ss, gg) = jax.lax.scan(
            body, 0, (idxs, scores, item_boost, exclude, b0s))
        cand_s = ss.reshape(1, shards * kl)
        cand_i = gg.reshape(1, shards * kl)
        neg, idx = jax.lax.sort((-cand_s, cand_i), dimension=1, num_keys=2)
        kk = min(k, shards * kl)
        return -neg[0, :kk], idx[0, :kk]

    return run


def host_sharded_score_user(indicator_list, k: int, n_items: int,
                            exclude, item_boost):
    """Host-sharded analog of ops.llr.score_user. ``indicator_list`` is
    [(HostShardedIndicators, membership[N] f32, boost)], ``exclude`` a
    bool [N] mask (True = suppressed), ``item_boost`` a float [N] vector;
    returns (scores[k'], idx[k']) with k' = min(k, n_items), bitwise
    identical to the unsharded scorer."""
    if not indicator_list:
        raise ValueError("host_sharded_score_user needs >=1 indicator type")
    shards0 = indicator_list[0][0]
    nl = shards0.rows_per_shard
    k_eff = min(int(k), int(n_items))
    fn = _host_ur_topk_fn(k_eff, len(indicator_list))
    idxs = tuple(h.idx for h, _m, _b in indicator_list)
    scores = tuple(h.score for h, _m, _b in indicator_list)
    membs = tuple(jnp.asarray(m, jnp.float32)
                  for _h, m, _b in indicator_list)
    boosts = tuple(jnp.float32(b) for _h, _m, b in indicator_list)
    # None ⇒ identity mask/boost: *1.0f and where(False, ...) are exact,
    # so the no-filter call stays bitwise identical to ops.llr.score_user.
    ib_host = (np.ones(int(n_items), np.float32) if item_boost is None
               else np.asarray(item_boost, np.float32))
    ex_host = (np.zeros(int(n_items), bool) if exclude is None
               else np.asarray(exclude, bool))
    ib = _stack_shards(ib_host, nl)
    ex = _stack_shards(ex_host, nl, fill=True)
    s, i = jax.device_get(
        fn(idxs, scores, membs, boosts, ib, ex, np.int32(n_items)))
    return s[:k_eff], i[:k_eff]
