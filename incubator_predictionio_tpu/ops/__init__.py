"""JAX/XLA numeric kernels: length-bucketed sparse layouts, ALS solves, segment
ops, top-k scoring, LLR co-occurrence. These are the TPU replacements for
the MLlib/Mahout internals the reference delegates to (SURVEY.md §2.8-2.9).
"""
