"""Correlated Cross-Occurrence (CCO) with log-likelihood-ratio scoring.

Reference behaviour: the Universal Recommender computes item-item
cross-occurrence matrices per event type with Apache Mahout's
SimilarityAnalysis.cooccurrencesIDSs (LLR-thresholded), then indexes the
indicators into Elasticsearch (SURVEY.md §2.8 row 5). TPU-native design
(SURVEY.md §7 step 10): co-occurrence counts are dense chunked matmuls on
the MXU — user-interaction matrices are scattered into dense [U_chunk, I]
slabs on device and C = Σ_chunks A_pᵀ A_s accumulates per primary/secondary
pair; Dunning's G² LLR is evaluated vectorized over the full count matrix;
top-k correlators per item are kept as static [I, K] index/score arrays
(the "index" that replaces Elasticsearch — scoring is then a gather+dot,
see models/universal_recommender.py).

Catalog-size note: the dense co-occurrence block is [I, I] f32 — fine to
~16k items on one chip (1GB); larger catalogs need item-axis chunking
(future work, the layout already permits it).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _xlogx(x):
    return jnp.where(x > 0, x * jnp.log(jnp.maximum(x, 1e-30)), 0.0)


def _entropy2(a, b):
    return _xlogx(a + b) - _xlogx(a) - _xlogx(b)


def llr_scores(k11, k12, k21, k22):
    """Dunning's G² over contingency counts (vectorized).

    Reference math: Mahout LogLikelihood.logLikelihoodRatio — G² =
    2·(H(row)+H(col)−H(matrix)) in the xlogx formulation.
    """
    row = _entropy2(k11 + k12, k21 + k22)
    col = _entropy2(k11 + k21, k12 + k22)
    mat = (
        _xlogx(k11 + k12 + k21 + k22)
        - _xlogx(k11) - _xlogx(k12) - _xlogx(k21) - _xlogx(k22)
    )
    g2 = 2.0 * (row + col - mat)
    # Guard tiny negatives from cancellation.
    return jnp.maximum(g2, 0.0)


@functools.partial(jax.jit, static_argnames=("n_items", "u_chunk", "n_ranges"))
def _cooccurrence_counts(pu, pi, su, si, n_items: int, u_chunk: int,
                         n_ranges: int):
    """C[i,j] = #users who interacted with primary item i and secondary
    item j. COO inputs -1-padded; the scan covers exactly
    ceil(n_users/u_chunk) user ranges. Dense per-user-chunk slabs keep the
    matmul on the MXU."""

    def body(c, k):
        # Build dense binary slabs for user range [k*Uc, (k+1)*Uc).
        def slab(uu, ii, lo):
            ok = (uu >= lo) & (uu < lo + u_chunk) & (ii >= 0)
            rows = jnp.where(ok, uu - lo, u_chunk)  # u_chunk = scratch row
            a = jnp.zeros((u_chunk + 1, n_items), jnp.float32)
            a = a.at[rows, jnp.maximum(ii, 0)].max(jnp.where(ok, 1.0, 0.0))
            return a[:u_chunk]

        lo = k * u_chunk
        ap = slab(pu, pi, lo)
        asec = slab(su, si, lo)
        c = c + jnp.einsum("ui,uj->ij", ap, asec,
                           preferred_element_type=jnp.float32)
        return c, None

    c0 = jnp.zeros((n_items, n_items), jnp.float32)
    c, _ = jax.lax.scan(body, c0, jnp.arange(n_ranges))
    return c


@dataclasses.dataclass
class Indicators:
    """Top-K LLR correlators per primary item (static shapes)."""

    idx: np.ndarray  # [I, K] int32, -1 = empty slot
    score: np.ndarray  # [I, K] f32 LLR

    @property
    def max_correlators(self) -> int:
        return self.idx.shape[1]


def cco_indicators(
    primary_u: np.ndarray,
    primary_i: np.ndarray,
    secondary_u: np.ndarray,
    secondary_i: np.ndarray,
    n_users: int,
    n_items: int,
    max_correlators: int = 50,
    llr_threshold: float = 0.0,
    u_chunk: int = 1024,
) -> Indicators:
    """Build the LLR-thresholded cross-occurrence indicator matrix between
    a primary event's items and a secondary event's items (same item-id
    space; self-co-occurrence when primary==secondary)."""

    def pad_chunk(u, i):
        u = np.asarray(u, np.int32)
        i = np.asarray(i, np.int32)
        # dedupe (user,item) pairs — binary interaction matrices
        pairs = np.unique(np.stack([u, i], 1), axis=0)
        u, i = pairs[:, 0], pairs[:, 1]
        n = len(u)
        target = max(((n + u_chunk - 1) // u_chunk) * u_chunk, u_chunk)
        pu = np.full(target, -1, np.int32)
        pi = np.full(target, -1, np.int32)
        pu[:n], pi[:n] = u, i
        return pu, pi

    pu, pi = pad_chunk(primary_u, primary_i)
    su, si = pad_chunk(secondary_u, secondary_i)
    n_ranges = max((n_users + u_chunk - 1) // u_chunk, 1)

    counts = _cooccurrence_counts(pu, pi, su, si, n_items, u_chunk, n_ranges)

    # Dunning contingency over DISTINCT USERS (Mahout semantics):
    # n_i = users who did the primary event on i, n_j = users who did the
    # secondary event on j, N = total users.
    n_i = np.bincount(pi[pi >= 0], minlength=n_items).astype(np.float32)
    n_j = np.bincount(si[si >= 0], minlength=n_items).astype(np.float32)
    n_total = float(n_users)

    k11 = counts
    k12 = jnp.maximum(jnp.asarray(n_i)[:, None] - counts, 0.0)
    k21 = jnp.maximum(jnp.asarray(n_j)[None, :] - counts, 0.0)
    k22 = jnp.maximum(n_total - k11 - k12 - k21, 0.0)
    llr = llr_scores(k11, k12, k21, k22)
    # No self-correlation on the diagonal and no score without counts.
    llr = jnp.where(counts > 0, llr, 0.0)
    llr = llr * (1.0 - jnp.eye(n_items, dtype=llr.dtype))
    if llr_threshold > 0:
        llr = jnp.where(llr >= llr_threshold, llr, 0.0)

    k = min(max_correlators, n_items)
    score, idx = jax.lax.top_k(llr, k)
    score = np.array(jax.device_get(score))
    idx = np.array(jax.device_get(idx), np.int32)
    idx[score <= 0] = -1
    return Indicators(idx=idx, score=score.astype(np.float32))


@functools.partial(jax.jit, static_argnames=("k",))
def _score_history(idx, score, membership, boost, k: int):
    """score_i = Σ_slots score[i,s]·membership[idx[i,s]] (gather+dot) —
    the ES similarity query replacement. membership: [I] 0/1 vector of the
    user's history for this event type."""
    m = jnp.where(idx >= 0, membership[jnp.maximum(idx, 0)], 0.0)
    s = jnp.einsum("ik,ik->i", score, m) * boost
    return s


def score_user(
    indicator_list: list[tuple[Indicators, np.ndarray, float]],
    k: int,
    exclude: Optional[np.ndarray] = None,
    item_boost: Optional[np.ndarray] = None,
):
    """Combine per-event-type indicator scores for one user's history.

    indicator_list: [(indicators, membership [I] f32, boost)] per event
    type. ``item_boost`` [I] multiplies scores BEFORE top-k so boosted
    items can enter the result set. Returns (scores[k], idx[k]) host
    arrays.
    """
    total = None
    for ind, membership, boost in indicator_list:
        s = _score_history(
            jnp.asarray(ind.idx), jnp.asarray(ind.score),
            jnp.asarray(membership), jnp.float32(boost), ind.idx.shape[1],
        )
        total = s if total is None else total + s
    if item_boost is not None:
        total = total * jnp.asarray(item_boost, total.dtype)
    if exclude is not None:
        total = jnp.where(jnp.asarray(exclude), -jnp.inf, total)
    kk = min(k, total.shape[0])
    out = jax.lax.top_k(total, kk)
    return jax.device_get(out)
