"""Correlated Cross-Occurrence (CCO) with log-likelihood-ratio scoring.

Reference behaviour: the Universal Recommender computes item-item
cross-occurrence matrices per event type with Apache Mahout's
SimilarityAnalysis.cooccurrencesIDSs (LLR-thresholded), then indexes the
indicators into Elasticsearch (SURVEY.md §2.8 row 5). TPU-native design
(SURVEY.md §7 step 10): co-occurrence counts are dense chunked matmuls on
the MXU — user-interaction matrices are scattered into dense [U_chunk, I]
slabs on device and C = Σ_chunks A_pᵀ A_s accumulates per primary/secondary
pair; Dunning's G² LLR is evaluated vectorized over the full count matrix;
top-k correlators per item are kept as static [I, K] index/score arrays
(the "index" that replaces Elasticsearch — scoring is then a gather+dot,
see models/universal_recommender.py).

Scale notes: events are pre-partitioned by user range on the host (sorted
slabs, like ops/blocked.py), so each scan step scatters only its own
events — the naive alternative of range-masking the whole event array per
step is quadratic and ~40x slower on TPU at 1M events. Slabs are int8
(binary, so exact) for the MXU's double-rate int8 mode with exact int32
accumulation (f32 only from the LLR stage on). Two
accumulation strategies, chosen by HBM budget: when the full [I, I]
f32 matrix fits a fraction of device memory, one scan over user ranges
builds each membership slab ONCE and accumulates the whole matrix
(then LLR + top-k per stripe slice — all one dispatch); bigger
catalogs stream [item_block, I] stripes through a bounded accumulator
(slabs rebuilt per stripe — the memory/compute trade). Both paths are
bit-identical (counts are exact integers; tested). Either
way only the [I, K] indicators materialize on the host.
"""

from __future__ import annotations

import dataclasses
import os
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


#: Heavy-user rank-range width: drives BOTH the heavy-slab partition
#: (h_per) and the device scan's u_chunk — a mismatch would silently
#: treat in-range offsets as padding sentinels and drop events.
_HEAVY_RANGE = 16


def _xlogx(x):
    return jnp.where(x > 0, x * jnp.log(jnp.maximum(x, 1e-30)), 0.0)


def _entropy2(a, b):
    return _xlogx(a + b) - _xlogx(a) - _xlogx(b)


def llr_scores(k11, k12, k21, k22):
    """Dunning's G² over contingency counts (vectorized).

    Reference math: Mahout LogLikelihood.logLikelihoodRatio — G² =
    2·(H(row)+H(col)−H(matrix)) in the xlogx formulation.
    """
    row = _entropy2(k11 + k12, k21 + k22)
    col = _entropy2(k11 + k21, k12 + k22)
    mat = (
        _xlogx(k11 + k12 + k21 + k22)
        - _xlogx(k11) - _xlogx(k12) - _xlogx(k21) - _xlogx(k22)
    )
    g2 = 2.0 * (row + col - mat)
    # Guard tiny negatives from cancellation.
    return jnp.maximum(g2, 0.0)


def _partition_by_user(u: np.ndarray, i: np.ndarray, u_chunk: int,
                       n_ranges: int, n_items: int,
                       assume_sorted: bool = False):
    """Host prep: sort (user, item) pairs by user range and lay them out
    as [n_ranges, E] slabs, so the device scan step for slab row r
    touches only events of one user range. A range's primary and
    secondary slabs must be COMPLETE for the per-step product to count
    every cross pair, so ranges are never split here — skewed heavy
    users are extracted beforehand (see ``cco_indicators``) to keep E
    near the mean.

    Returns (eu [n_ranges, E], ei [n_ranges, E]): eu holds the user's
    LOCAL offset within its range (padding sentinel = u_chunk — no
    per-row base array needed on device), ei the item id (padding 0,
    masked by the sentinel). Both upload uint16 when their value range
    fits (they nearly always do: u_chunk defaults to 2048, catalogs are
    rarely >65k items) — half the slab bytes of int32, which matters
    because the slab upload is a dominant warm-train cost on
    remote-attached chips."""
    # Events whose user id falls outside [0, n_ranges*u_chunk) are dropped
    # (contract: user ids < n_users; the pre-rewrite slab mask silently
    # ignored them too, and a bad id must not corrupt the layout).
    valid = (u >= 0) & (u < n_ranges * u_chunk)
    u, i = u[valid], i[valid]
    if assume_sorted:
        # dedupe already emits (user, item)-sorted pairs; re-argsorting
        # 8M rows cost ~0.3 s of pure host time per event set
        us, is_ = u, i
    else:
        order = np.argsort(u, kind="stable")
        us, is_ = u[order], i[order]
    chunk_of = (us // u_chunk).astype(np.int64)
    counts = np.bincount(chunk_of, minlength=n_ranges)
    e = max(int(counts.max()), 1) if counts.size else 1

    starts = np.zeros(n_ranges + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(len(us)) - starts[chunk_of]
    u_dtype = np.uint16 if u_chunk < 0xFFFF else np.int32
    i_dtype = np.uint16 if n_items <= 0xFFFF else np.int32
    eu = np.full((n_ranges, e), u_chunk, u_dtype)   # sentinel = u_chunk
    ei = np.zeros((n_ranges, e), i_dtype)
    eu[chunk_of, pos] = (us - chunk_of * u_chunk).astype(u_dtype)
    ei[chunk_of, pos] = is_.astype(i_dtype)
    return eu, ei


def _slab(uu, ii, u_chunk: int, n_items: int):
    """One range's binary int8 membership slab [u_chunk, n_items] from
    (local user offset, item) event pairs; the sentinel offset u_chunk
    lands padding on a scratch row that is sliced away.

    int8, not bf16: binary membership is exact in any dtype, and the
    v5e MXU runs int8 contractions at ~2x its bf16 rate (197 TOPS vs
    98 TFLOPs — measured 2.8x on the UR shapes). Counts accumulate in
    int32 (≤ n_users, exact) and widen to f32 only at the LLR stage.

    Built as a FLAT 1-D scatter-add then reshaped: the 2-D scatter-max
    lowered to TPU's serialized scatter path (~457 ns/element — measured
    3.6 s just building slabs for the UR bench), while the 1-D add runs
    ~28x faster. Events are deduped upstream, so each (u, i) lands
    exactly once and add ≡ max ≡ set (bit-identical counts)."""
    # int64 flat indices when the slab exceeds int32 addressing (the
    # striped path serves multi-million-item catalogs)
    idx_dtype = (jnp.int32 if (u_chunk + 1) * n_items < 2**31
                 else jnp.int64)
    flat = uu.astype(idx_dtype) * n_items + ii.astype(idx_dtype)
    a = jnp.zeros(((u_chunk + 1) * n_items,), jnp.int8)
    a = a.at[flat].add(jnp.int8(1))
    return a.reshape(u_chunk + 1, n_items)[:u_chunk]


@functools.partial(jax.jit, static_argnames=("n_items", "u_chunk", "block"))
def _cooccurrence_stripe(peu, pei, seu, sei, lo_item,
                         n_items: int, u_chunk: int, block: int):
    """One stripe C[lo_item:lo_item+block, :] of the co-occurrence
    matrix: Σ over slab rows of slab_p[:, stripe]ᵀ @ slab_s. Inputs are
    the host-partitioned [n_rows, E] event slabs (local user offsets,
    sentinel u_chunk = padding); each scan step scatters only its own
    row's events.

    Heavy users are not in the light slabs; ``cco_indicators`` routes
    them through this same kernel with rank-renumbered ids and small
    rank ranges."""

    def body(c, chunk):
        eu_p, ei_p, eu_s, ei_s = chunk
        ap = jax.lax.dynamic_slice(
            _slab(eu_p, ei_p, u_chunk, n_items), (0, lo_item),
            (u_chunk, block))
        asec = _slab(eu_s, ei_s, u_chunk, n_items)
        c = c + jnp.einsum("ui,uj->ij", ap, asec,
                           preferred_element_type=jnp.int32)
        return c, None

    c0 = jnp.zeros((block, n_items), jnp.int32)
    c, _ = jax.lax.scan(body, c0, (peu, pei, seu, sei))
    return c


@functools.partial(jax.jit, static_argnames=("n_items", "u_chunk", "h_chunk"))
def _full_cooccurrence(light, heavy, n_items: int, u_chunk: int,
                       h_chunk: int):
    """The whole [I, I] co-occurrence matrix in one scan over user
    ranges — each range's slabs are built ONCE (the striped kernel
    rebuilds them per stripe; at 20k items that redundant scatter was
    ~60% of UR's device time). Costs n_items^2 * 4 bytes of HBM for
    the accumulator, so ``cco_indicators`` only routes here when that
    fits (PIO_UR_FULL_MATRIX_ELEMS caps it; the striped path remains
    for big catalogs). Counts are exact integers in int32, so both
    paths produce IDENTICAL results (tested)."""

    def mk_body(chunk_rows: int):
        def body(c, chunk):
            eu_p, ei_p, eu_s, ei_s = chunk
            ap = _slab(eu_p, ei_p, chunk_rows, n_items)
            asec = _slab(eu_s, ei_s, chunk_rows, n_items)
            c = c + jnp.einsum("ui,uj->ij", ap, asec,
                               preferred_element_type=jnp.int32)
            return c, None
        return body

    c0 = jnp.zeros((n_items, n_items), jnp.int32)
    c, _ = jax.lax.scan(mk_body(u_chunk), c0, light)
    if heavy is not None:
        c, _ = jax.lax.scan(mk_body(h_chunk), c, heavy)
    return c


def _pad_ranges(arrs, mult: int, u_chunk: int):
    """Pad the leading (range) axis to a device-count multiple with
    sentinel-only rows (local offset u_chunk = padding → zero slab →
    contributes nothing to the accumulate)."""
    n = arrs[0].shape[0]
    target = -(-n // mult) * mult
    if target == n:
        return arrs
    out = []
    for j, a in enumerate(arrs):
        fill = u_chunk if j % 2 == 0 else 0   # (eu, ei) alternating
        pad = np.full((target - n, a.shape[1]), fill, a.dtype)
        out.append(np.concatenate([np.asarray(a), pad], axis=0))
    return tuple(out)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "n_items", "u_chunk", "h_chunk", "block", "k",
    "llr_threshold"))
def _full_cco_topk_sharded(light, heavy, lo_effs, n_i, n_j, n_total, *,
                           mesh, n_items: int, u_chunk: int, h_chunk: int,
                           block: int, k: int, llr_threshold: float):
    """Multi-chip full-matrix path: user ranges shard over DATA_AXIS —
    each device scans only its local ranges and the per-device partial
    [I, I] counts psum over ICI (counts are exact small integers in
    f32, so the psum is exact and the result is bit-identical to the
    single-device path — tested on the virtual mesh). LLR + top-k run
    replicated afterwards inside the SAME jit. ``mesh`` is a static
    arg (Mesh is hashable), so repeat trains at the same shapes reuse
    one executable like every other kernel here."""
    from ..common.jax_compat import pcast, shard_map
    from jax.sharding import PartitionSpec as _P
    from ..parallel.mesh import DATA_AXIS as _D

    def counts_fn(light_l, heavy_l):
        def mk_body(chunk_rows: int):
            def body(c, chunk):
                eu_p, ei_p, eu_s, ei_s = chunk
                ap = _slab(eu_p, ei_p, chunk_rows, n_items)
                asec = _slab(eu_s, ei_s, chunk_rows, n_items)
                return c + jnp.einsum(
                    "ui,uj->ij", ap, asec,
                    preferred_element_type=jnp.int32), None
            return body

        c0 = jnp.zeros((n_items, n_items), jnp.int32)
        # shard_map's varying-manual-axes typing: the carry starts as a
        # replicated constant but the body output varies over the data
        # axis — mark it varying up front (no-op on jax 0.4.x, where
        # check_rep=False already treats every value as varying)
        c0 = pcast(c0, (_D,), to="varying")
        c, _ = jax.lax.scan(mk_body(u_chunk), c0, light_l)
        if heavy_l is not None:
            c, _ = jax.lax.scan(mk_body(h_chunk), c, heavy_l)
        return jax.lax.psum(c, _D)

    spec_rows = _P(_D, None)
    in_specs = (tuple(spec_rows for _ in light),
                None if heavy is None else tuple(spec_rows for _ in heavy))
    c = shard_map(
        counts_fn, mesh=mesh,
        in_specs=in_specs, out_specs=_P(),
    )(light, heavy)

    def body(carry, lo_eff):
        counts = jax.lax.dynamic_slice(c, (lo_eff, 0), (block, n_items))
        n_i_stripe = jax.lax.dynamic_slice(n_i, (lo_eff,), (block,))
        s, ix = _stripe_topk(counts, n_i_stripe, n_j, lo_eff, n_total,
                             k=k, llr_threshold=llr_threshold)
        return carry, (s, ix)

    _, (ss, ixs) = jax.lax.scan(body, 0, lo_effs)
    return ss, ixs


@functools.partial(jax.jit, static_argnames=(
    "n_items", "u_chunk", "h_chunk", "block", "k", "llr_threshold",
    "self_flags"))
def _full_cco_topk_multi(light_p, light_secs, heavy_p, heavy_secs, lo_effs,
                         n_i, n_js, n_total, *, n_items: int, u_chunk: int,
                         h_chunk: int, block: int, k: int,
                         llr_threshold: float, self_flags: tuple):
    """ALL of one primary's cross-occurrence pairs in ONE dispatch: the
    user-range scan builds each range's PRIMARY membership slab once and
    accumulates every secondary's [I, I] matrix against it (self-pairs
    reuse the primary slab outright — no second scatter, no second
    upload). The per-pair path scatters the primary slab S times and
    uploads the primary events S times; for the UR bench (buy→buy +
    buy→view) the fusion removes a third of the event-slab upload bytes
    and half the primary scatters. Counts stay exact small integers in
    f32 → bit-identical to per-pair calls (tested).

    light_secs/heavy_secs: (eu, ei) pairs for NON-self secondaries, in
    output order; self_flags marks which outputs take the primary slab.
    n_js: [S, I] per-secondary distinct-user item counts."""

    n_sec = len(self_flags)
    c0 = tuple(jnp.zeros((n_items, n_items), jnp.int32)
               for _ in range(n_sec))
    xs = tuple(light_p) + tuple(x for pair in light_secs for x in pair)
    cs, _ = jax.lax.scan(_mk_multi_body(self_flags, n_items, u_chunk),
                         c0, xs)
    if heavy_p is not None:
        xs_h = tuple(heavy_p) + tuple(x for pair in heavy_secs for x in pair)
        cs, _ = jax.lax.scan(_mk_multi_body(self_flags, n_items, h_chunk),
                             cs, xs_h)

    return _topk_per_secondary(cs, n_js, n_i, lo_effs, n_total,
                               n_items=n_items, block=block, k=k,
                               llr_threshold=llr_threshold)


def _mk_multi_body(self_flags: tuple, n_items: int, chunk_rows: int):
    """Scan body shared by the fused single-device and sharded kernels:
    build the primary slab once, accumulate every pair against it."""
    def body(cs, chunk):
        ap = _slab(chunk[0], chunk[1], chunk_rows, n_items)
        outs, r = [], 2
        for is_self in self_flags:
            if is_self:
                a2 = ap
            else:
                a2 = _slab(chunk[r], chunk[r + 1], chunk_rows, n_items)
                r += 2
            outs.append(cs[len(outs)] + jnp.einsum(
                "ui,uj->ij", ap, a2,
                preferred_element_type=jnp.int32))
        return tuple(outs), None
    return body


def _topk_per_secondary(cs, n_js, n_i, lo_effs, n_total, *, n_items: int,
                        block: int, k: int, llr_threshold: float):
    """Per-secondary stripe LLR + top-k loop shared by every full-matrix
    kernel variant (single/sharded, single-pair/fused)."""
    outs = []
    for s_idx in range(len(cs)):
        c = cs[s_idx]
        n_j = n_js[s_idx]

        def body(carry, lo_eff, c=c, n_j=n_j):
            counts = jax.lax.dynamic_slice(c, (lo_eff, 0), (block, n_items))
            n_i_stripe = jax.lax.dynamic_slice(n_i, (lo_eff,), (block,))
            s, ix = _stripe_topk(counts, n_i_stripe, n_j, lo_eff, n_total,
                                 k=k, llr_threshold=llr_threshold)
            return carry, (s, ix)

        _, (ss, ixs) = jax.lax.scan(body, 0, lo_effs)
        outs.append((ss, ixs))
    return tuple(outs)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "n_items", "u_chunk", "h_chunk", "block", "k",
    "llr_threshold", "self_flags"))
def _full_cco_topk_multi_sharded(light_p, light_secs, heavy_p, heavy_secs,
                                 lo_effs, n_i, n_js, n_total, *, mesh,
                                 n_items: int, u_chunk: int, h_chunk: int,
                                 block: int, k: int, llr_threshold: float,
                                 self_flags: tuple):
    """Multi-chip variant of _full_cco_topk_multi: user ranges shard
    over DATA_AXIS, every device scans only its local ranges building
    the primary slab once per range for ALL pairs, and the per-device
    partial count matrices psum over ICI (exact int32 → bit-identical
    to per-pair and to single-device; tested on the virtual mesh).
    heavy_p/heavy_secs use () for absent (static pytree shape)."""
    from ..common.jax_compat import pcast, shard_map
    from jax.sharding import PartitionSpec as _P
    from ..parallel.mesh import DATA_AXIS as _D

    n_sec = len(self_flags)

    def counts_fn(lp, lsecs, hp, hsecs):
        c0 = tuple(
            pcast(jnp.zeros((n_items, n_items), jnp.int32),
                  (_D,), to="varying")
            for _ in range(n_sec))
        xs = tuple(lp) + tuple(x for pair in lsecs for x in pair)
        cs, _ = jax.lax.scan(_mk_multi_body(self_flags, n_items, u_chunk),
                             c0, xs)
        if len(hp):
            xs_h = tuple(hp) + tuple(x for pair in hsecs for x in pair)
            cs, _ = jax.lax.scan(
                _mk_multi_body(self_flags, n_items, h_chunk), cs, xs_h)
        return tuple(jax.lax.psum(c, _D) for c in cs)

    rows = _P(_D, None)

    def specs_like(tree):
        return jax.tree.map(lambda _: rows, tree,
                            is_leaf=lambda x: x is None)

    cs = shard_map(
        counts_fn, mesh=mesh,
        in_specs=(specs_like(light_p), specs_like(light_secs),
                  specs_like(heavy_p), specs_like(heavy_secs)),
        out_specs=tuple(_P() for _ in range(n_sec)),
    )(light_p, light_secs, heavy_p, heavy_secs)

    return _topk_per_secondary(cs, n_js, n_i, lo_effs, n_total,
                               n_items=n_items, block=block, k=k,
                               llr_threshold=llr_threshold)


@functools.partial(jax.jit, static_argnames=(
    "n_items", "u_chunk", "h_chunk", "block", "k", "llr_threshold"))
def _full_cco_topk(light, heavy, lo_effs, n_i, n_j, n_total,
                   n_items: int, u_chunk: int, h_chunk: int,
                   block: int, k: int, llr_threshold: float):
    """Full-matrix accumulate + per-stripe LLR/top-k as ONE dispatch
    (per-dispatch RTT through remote tunnels is why the striped path
    got _all_stripes; the full path keeps the same property)."""
    c = _full_cooccurrence(light, heavy, n_items=n_items,
                           u_chunk=u_chunk, h_chunk=h_chunk)

    def body(carry, lo_eff):
        counts = jax.lax.dynamic_slice(c, (lo_eff, 0), (block, n_items))
        n_i_stripe = jax.lax.dynamic_slice(n_i, (lo_eff,), (block,))
        s, ix = _stripe_topk(counts, n_i_stripe, n_j, lo_eff, n_total,
                             k=k, llr_threshold=llr_threshold)
        return carry, (s, ix)

    _, (ss, ixs) = jax.lax.scan(body, 0, lo_effs)
    return ss, ixs


def _full_matrix_elem_cap() -> int:
    """Element budget for the [I, I] accumulator: an explicit
    PIO_UR_FULL_MATRIX_ELEMS wins (malformed values fall back with a
    warning rather than crashing training); otherwise the accumulator
    may use 1/4 of the device's reported memory — scan carries alias
    (no double buffer), and the remaining 3/4 leaves head-room for the
    bf16 slabs and LLR/top-k intermediates. TPUs whose tunnel reports
    no memory stats assume the fleet-minimum 8 GiB."""
    from ..common import envknobs

    raw = envknobs.env_str("PIO_UR_FULL_MATRIX_ELEMS", "")
    if raw:
        explicit = envknobs.env_int("PIO_UR_FULL_MATRIX_ELEMS", 0,
                                    float_ok=True)
        if explicit > 0:
            return explicit
        import warnings

        warnings.warn(
            f"PIO_UR_FULL_MATRIX_ELEMS={raw!r} is not a positive "
            "number; using the device-derived default", stacklevel=2)
    limit = 0
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit <= 0 and dev.platform == "tpu":
            # remote-PJRT tunnels report no memory stats; the smallest
            # TPU HBM in the supported fleet is 8 GiB per core
            limit = 8 * 1024 ** 3
    except Exception:
        pass
    if limit <= 0:
        limit = 4 * 1024 ** 3
    # 1/4 of memory for the f32 accumulator: scan carries alias (no
    # double buffer), leaving head-room for slabs + LLR intermediates
    return limit // 4 // 4


@dataclasses.dataclass
class Indicators:
    """Top-K LLR correlators per primary item (static shapes)."""

    idx: np.ndarray  # [I, K] int32, -1 = empty slot
    score: np.ndarray  # [I, K] f32 LLR

    @property
    def max_correlators(self) -> int:
        return self.idx.shape[1]


@functools.partial(jax.jit, static_argnames=("k", "llr_threshold"))
def _stripe_topk(counts, n_i_stripe, n_j, lo_item, n_total,
                 k: int, llr_threshold: float):
    """LLR + top-k for one [block, I] stripe of counts. Dunning
    contingency over DISTINCT USERS (Mahout semantics): n_i = users who
    did the primary event on item i, n_j likewise for the secondary
    event, N = total users."""
    block, n_items = counts.shape
    # counts arrive as exact int32 from the int8 MXU accumulate; LLR
    # math runs in f32 (counts <= n_users << 2^24, exact)
    counts = counts.astype(jnp.float32)
    k11 = counts
    k12 = jnp.maximum(n_i_stripe[:, None] - counts, 0.0)
    k21 = jnp.maximum(n_j[None, :] - counts, 0.0)
    k22 = jnp.maximum(n_total - k11 - k12 - k21, 0.0)
    llr = llr_scores(k11, k12, k21, k22)
    # No self-correlation on the diagonal and no score without counts.
    row_ids = lo_item + jnp.arange(block, dtype=jnp.int32)
    col_ids = jnp.arange(n_items, dtype=jnp.int32)
    llr = jnp.where(counts > 0, llr, 0.0)
    llr = jnp.where(row_ids[:, None] == col_ids[None, :], 0.0, llr)
    if llr_threshold > 0:
        llr = jnp.where(llr >= llr_threshold, llr, 0.0)
    return jax.lax.top_k(llr, k)


@functools.partial(jax.jit, static_argnames=(
    "n_items", "u_chunk", "block", "k", "llr_threshold", "h_chunk"))
def _all_stripes(lo_effs, light, heavy, n_i, n_j, n_total,
                 n_items: int, u_chunk: int, block: int, k: int,
                 llr_threshold: float, h_chunk: int):
    """Every item stripe in ONE dispatch: lax.scan over the stripe
    origins runs cooccurrence + LLR + top-k per stripe on device and
    returns the stacked [n_stripes, block, k] results — one download
    instead of a dispatch + device_get round trip per stripe (through
    the remote tunnel each of those cost a full RTT, which dominated
    the UR warm train)."""
    def body(carry, lo_eff):
        counts = _cooccurrence_stripe(
            *light, lo_eff, n_items=n_items, u_chunk=u_chunk, block=block)
        if heavy is not None:
            counts = counts + _cooccurrence_stripe(
                *heavy, lo_eff, n_items=n_items, u_chunk=h_chunk,
                block=block)
        n_i_stripe = jax.lax.dynamic_slice(n_i, (lo_eff,), (block,))
        s, ix = _stripe_topk(counts, n_i_stripe, n_j, lo_eff, n_total,
                             k=k, llr_threshold=llr_threshold)
        return carry, (s, ix)

    _, (ss, ixs) = jax.lax.scan(body, 0, lo_effs)
    return ss, ixs


@functools.partial(jax.jit, static_argnames=(
    "mesh", "n_items", "u_chunk", "block", "k", "llr_threshold",
    "h_chunk"))
def _all_stripes_sharded(lo_effs, light, heavy, n_i, n_j, n_total, *,
                         mesh, n_items: int, u_chunk: int, block: int,
                         k: int, llr_threshold: float, h_chunk: int):
    """Multi-chip STRIPED path (catalogs whose [I, I] accumulator does
    not fit the budget): for each stripe, every device scans its local
    user ranges into a [block, I] partial and the partials psum over
    ICI; LLR + top-k stay replicated. Bit-identical to the
    single-device striped path (exact integer counts)."""
    from ..common.jax_compat import pcast, shard_map
    from jax.sharding import PartitionSpec as _P
    from ..parallel.mesh import DATA_AXIS as _D

    def all_local(light_l, heavy_l):
        def one_stripe(lo_eff):
            def mk_body(chunk_rows: int):
                def body(c, chunk):
                    eu_p, ei_p, eu_s, ei_s = chunk
                    ap = jax.lax.dynamic_slice(
                        _slab(eu_p, ei_p, chunk_rows, n_items),
                        (0, lo_eff), (chunk_rows, block))
                    asec = _slab(eu_s, ei_s, chunk_rows, n_items)
                    return c + jnp.einsum(
                        "ui,uj->ij", ap, asec,
                        preferred_element_type=jnp.int32), None
                return body

            c0 = pcast(
                jnp.zeros((block, n_items), jnp.int32), (_D,),
                to="varying")
            c, _ = jax.lax.scan(mk_body(u_chunk), c0, light_l)
            if heavy_l is not None:
                c, _ = jax.lax.scan(mk_body(h_chunk), c, heavy_l)
            return jax.lax.psum(c, _D)

        def body(carry, lo_eff):
            counts = one_stripe(lo_eff)
            n_i_stripe = jax.lax.dynamic_slice(n_i, (lo_eff,), (block,))
            s, ix = _stripe_topk(counts, n_i_stripe, n_j, lo_eff,
                                 n_total, k=k,
                                 llr_threshold=llr_threshold)
            return carry, (s, ix)

        _, (ss, ixs) = jax.lax.scan(body, 0, lo_effs)
        return ss, ixs

    spec_rows = _P(_D, None)
    in_specs = (tuple(spec_rows for _ in light),
                None if heavy is None else tuple(spec_rows for _ in heavy))
    return shard_map(
        all_local, mesh=mesh, in_specs=in_specs, out_specs=_P(),
    )(light, heavy)


def cco_indicators(
    primary_u: np.ndarray,
    primary_i: np.ndarray,
    secondary_u: np.ndarray,
    secondary_i: np.ndarray,
    n_users: int,
    n_items: int,
    max_correlators: int = 50,
    llr_threshold: float = 0.0,
    u_chunk: int = 2048,
    item_block: int = 4096,
    mesh=None,
) -> Indicators:
    """Build the LLR-thresholded cross-occurrence indicator matrix between
    a primary event's items and a secondary event's items (same item-id
    space; self-co-occurrence when primary==secondary). Memory strategy
    per _full_matrix_elem_cap; with a multi-device ``mesh`` the
    full-matrix accumulate shards user ranges over DATA_AXIS (per-device
    scans + one exact psum over ICI) — bit-identical results, linear
    range-scan scaling."""

    # Packed-key dedupe (native when available); output is
    # (user, item)-sorted, which every partition below relies on.
    pu, pi, cnt_p = _dedupe_pair(primary_u, primary_i, n_users, n_items)
    su, si, cnt_s = _dedupe_pair(secondary_u, secondary_i, n_users, n_items)
    n_ranges = max((n_users + u_chunk - 1) // u_chunk, 1)

    # Heavy-user extraction: a user with far more interactions than the
    # mean would inflate every slab row's width E (user ranges cannot be
    # split — a scan step's product needs the range's COMPLETE
    # primary+secondary events to count every cross pair). Heavy users
    # are renumbered onto a dense RANK space and processed through the
    # SAME striped kernel with u_chunk-sized rank ranges: each rank range
    # holds few (very active) users, so its slab width stays bounded
    # while every heavy range fits the same [u_chunk+1, I] slab budget.
    per_user = cnt_p + cnt_s
    mean_pu = max(float(per_user.sum()) / max(n_users, 1), 1.0)
    heavy_cap = max(int(16 * mean_pu), 256)
    heavy_users = np.nonzero(per_user > heavy_cap)[0]
    n_heavy = int(len(heavy_users))
    if n_heavy:
        rank = np.full(n_users, -1, np.int64)
        rank[heavy_users] = np.arange(n_heavy)

        def split_heavy(u, i):
            hm = rank[u] >= 0
            return (u[~hm], i[~hm],
                    rank[u[hm]].astype(np.int32), i[hm].astype(np.int32))

        pu_l, pi_l, hp_u, hp_i = split_heavy(pu, pi)
        su_l, si_l, hs_u, hs_i = split_heavy(su, si)
        # FEW heavy users per rank range (16), so one range's slab width
        # stays ≈ 16 heavy histories, not u_chunk of them. The slab
        # height is the range size, so heavy slabs are [17, I] — tiny.
        h_ranges = max((n_heavy + _HEAVY_RANGE - 1) // _HEAVY_RANGE, 1)
        h_per = _HEAVY_RANGE
        hpeu, hpei = _partition_by_user(hp_u, hp_i, h_per, h_ranges,
                                        n_items, assume_sorted=True)
        hseu, hsei = _partition_by_user(hs_u, hs_i, h_per, h_ranges,
                                        n_items, assume_sorted=True)
    else:
        pu_l, pi_l, su_l, si_l = pu, pi, su, si

    peu, pei = _partition_by_user(pu_l, pi_l, u_chunk, n_ranges, n_items, assume_sorted=True)
    seu, sei = _partition_by_user(su_l, si_l, u_chunk, n_ranges, n_items, assume_sorted=True)

    n_i = np.bincount(pi, minlength=n_items).astype(np.float32)
    n_j = jnp.asarray(np.bincount(si, minlength=n_items).astype(np.float32))
    n_total = jnp.float32(n_users)

    k = min(max_correlators, n_items)
    block = min(item_block, n_items)

    # Last stripe may be ragged: compute a full block ending at the
    # catalog edge and slice the overlap off (same compiled shape).
    los = list(range(0, n_items, block))
    lo_effs_np = np.array([min(lo, n_items - block) for lo in los], np.int32)
    n_mesh_dev = int(mesh.devices.size) if mesh is not None else 1
    full_fits = n_items * n_items <= _full_matrix_elem_cap()
    if n_mesh_dev > 1:
        # multi-chip prep, shared by both strategies: pad the range
        # axis to a device multiple; the slabs upload ONCE, sharded by
        # the jit (no eager single-device copy first)
        light_sh = _pad_ranges((peu, pei, seu, sei), n_mesh_dev, u_chunk)
        heavy_sh = None
        if n_heavy:
            heavy_sh = _pad_ranges((hpeu, hpei, hseu, hsei),
                                   n_mesh_dev, _HEAVY_RANGE)
        fn = _full_cco_topk_sharded if full_fits else _all_stripes_sharded
        if full_fits:
            ss, ixs = jax.device_get(fn(
                light_sh, heavy_sh, jnp.asarray(lo_effs_np),
                jnp.asarray(n_i), n_j, n_total, mesh=mesh,
                n_items=n_items, u_chunk=u_chunk, h_chunk=_HEAVY_RANGE,
                block=block, k=k, llr_threshold=llr_threshold))
        else:
            ss, ixs = jax.device_get(fn(
                jnp.asarray(lo_effs_np), light_sh, heavy_sh,
                jnp.asarray(n_i), n_j, n_total, mesh=mesh,
                n_items=n_items, u_chunk=u_chunk, block=block, k=k,
                llr_threshold=llr_threshold, h_chunk=_HEAVY_RANGE))
    else:
        n_i_dev = jnp.asarray(n_i)
        light_dev = tuple(map(jnp.asarray, (peu, pei, seu, sei)))
        heavy_arg = (tuple(map(jnp.asarray, (hpeu, hpei, hseu, hsei)))
                     if n_heavy else None)
        if full_fits:
            # full-matrix path: every slab built once (_full_cooccurrence)
            ss, ixs = jax.device_get(_full_cco_topk(
                light_dev, heavy_arg, jnp.asarray(lo_effs_np), n_i_dev,
                n_j, n_total, n_items=n_items, u_chunk=u_chunk,
                h_chunk=_HEAVY_RANGE, block=block, k=k,
                llr_threshold=llr_threshold))
        else:
            ss, ixs = jax.device_get(_all_stripes(
                jnp.asarray(lo_effs_np), light_dev, heavy_arg,
                n_i_dev, n_j, n_total,
                n_items=n_items, u_chunk=u_chunk, block=block, k=k,
                llr_threshold=llr_threshold, h_chunk=_HEAVY_RANGE,
            ))

    return _gather_indicators(ss, ixs, los, lo_effs_np, block, n_items)


def _dedupe_pair(u, i, n_users: int, n_items: int):
    """Distinct (user, item) pairs sorted by (user, item), out-of-range
    ids dropped. Native path: counting-sort by user + small per-user
    sorts (two linear passes — a global 16-bit-radix sort was tried
    first and LOST to numpy's introsort at 8M keys, 0.76 s vs 0.31 s;
    the per-user bucketing beats both at ~0.15 s). The numpy packed-key
    np.unique fallback is order-identical (tested).

    Returns (users, items, per_user_distinct_counts)."""
    try:
        from ..native import pair_dedupe

        return pair_dedupe(np.asarray(u), np.asarray(i), n_users, n_items)
    except Exception:  # noqa: BLE001 - native optional; numpy identical
        pass
    u = np.asarray(u, np.int64)
    i = np.asarray(i, np.int64)
    valid = (i >= 0) & (i < n_items) & (u >= 0) & (u < n_users)
    u, i = u[valid], i[valid]
    key = np.unique(u * n_items + i)
    du = (key // n_items).astype(np.int32)
    return (du, (key % n_items).astype(np.int32),
            np.bincount(du, minlength=n_users).astype(np.int64))


def _gather_indicators(ss, ixs, los, lo_effs_np, block, n_items) -> Indicators:
    """Stacked per-stripe device results → host [I, K] Indicators
    (ragged last stripe sliced; zero-score slots → -1)."""
    idx_parts, score_parts = [], []
    for j, lo in enumerate(los):
        b = min(block, n_items - lo)
        skip = lo - int(lo_effs_np[j])
        score_parts.append(np.asarray(ss[j])[skip:skip + b])
        idx_parts.append(np.asarray(ixs[j])[skip:skip + b])
    score = np.concatenate(score_parts, axis=0)
    idx = np.concatenate(idx_parts, axis=0).astype(np.int32)
    idx[score <= 0] = -1
    return Indicators(idx=idx, score=score.astype(np.float32))


def cco_indicators_multi(
    primary_u: np.ndarray,
    primary_i: np.ndarray,
    secondaries: dict,
    n_users: int,
    n_items: int,
    max_correlators: int = 50,
    llr_threshold: float = 0.0,
    u_chunk: int = 2048,
    item_block: int = 4096,
    mesh=None,
) -> dict:
    """All cross-occurrence indicator matrices of ONE primary event in a
    single fused device program (reference: the UR trains Mahout
    SimilarityAnalysis per event-type pair; here the pairs share the
    primary's dedupe, host partition, upload, and per-range membership
    slab — see _full_cco_topk_multi). ``secondaries`` maps name →
    (u, i); passing the primary's OWN arrays (by identity) marks a
    self-pair, which reuses the primary slabs end to end.

    On a multi-device mesh the same fusion shards user ranges over
    DATA_AXIS with psum'd partial counts (_full_cco_topk_multi_sharded).
    Falls back to per-pair ``cco_indicators`` calls when the fused
    accumulators would not fit the HBM budget (each pair then gets the
    full-vs-striped choice independently). Results are bit-identical to
    per-pair calls either way (exact integer counts; tested)."""
    names = list(secondaries.keys())
    n_sec = len(names)
    n_mesh_dev = int(mesh.devices.size) if mesh is not None else 1
    # fused path budget: all S accumulators together may use HALF the
    # device memory (the single-pair cap allows one accumulator a
    # quarter — same headroom reasoning, S of them share it)
    fused_fits = n_sec * n_items * n_items <= 2 * _full_matrix_elem_cap()
    if n_sec == 0:
        return {}
    if not fused_fits or n_sec == 1:
        return {
            name: cco_indicators(
                primary_u, primary_i, su, si, n_users, n_items,
                max_correlators=max_correlators,
                llr_threshold=llr_threshold, u_chunk=u_chunk,
                item_block=item_block, mesh=mesh)
            for name, (su, si) in secondaries.items()
        }

    pu, pi, per_user = _dedupe_pair(primary_u, primary_i, n_users, n_items)
    per_user = per_user.astype(np.int64, copy=True)
    deduped = {}
    for name, (su, si) in secondaries.items():
        if su is primary_u and si is primary_i:
            deduped[name] = None  # self-pair: reuse primary everywhere
        else:
            du, di, cnt = _dedupe_pair(su, si, n_users, n_items)
            deduped[name] = (du, di)
            # Heavy-user extraction over the COMBINED activity (primary
            # + every distinct secondary): the threshold only shapes the
            # layout, never the counts, so any consistent choice keeps
            # results identical.
            per_user += cnt
    mean_pu = max(float(per_user.sum()) / max(n_users, 1), 1.0)
    heavy_cap = max(int(16 * mean_pu), 256)
    heavy_users = np.nonzero(per_user > heavy_cap)[0]
    n_heavy = int(len(heavy_users))
    rank = None
    if n_heavy:
        rank = np.full(n_users, -1, np.int64)
        rank[heavy_users] = np.arange(n_heavy)

    def split_heavy(u, i):
        if rank is None:
            return u, i, None, None
        hm = rank[u] >= 0
        return (u[~hm], i[~hm],
                rank[u[hm]].astype(np.int32), i[hm].astype(np.int32))

    n_ranges = max((n_users + u_chunk - 1) // u_chunk, 1)
    h_ranges = max((n_heavy + _HEAVY_RANGE - 1) // _HEAVY_RANGE, 1)

    def partition_put(u, i):
        """Partition (one-pass native C when available — the numpy
        fancy-index layout measured ~1.0 s of pure host time at the UR
        bench's 10M pairs) + START the async uploads immediately, so a
        later secondary's host partition overlaps this one's transfer."""
        try:
            from ..native import cco_partition

            light, heavy, counts = cco_partition(
                u, i, rank, n_users, u_chunk, n_ranges, n_items,
                _HEAVY_RANGE, h_ranges)
        except Exception:  # noqa: BLE001 - native optional; layout identical
            lu, li, hu, hi = split_heavy(u, i)
            light = _partition_by_user(lu, li, u_chunk, n_ranges, n_items,
                                       assume_sorted=True)
            heavy = None
            if n_heavy:
                heavy = _partition_by_user(hu, hi, _HEAVY_RANGE, h_ranges,
                                           n_items, assume_sorted=True)
            counts = np.bincount(i, minlength=n_items)
        if n_mesh_dev > 1:
            # multi-chip: pad the range axis to a device multiple and
            # hand the jit the HOST arrays — it uploads them sharded
            # (an eager put would land everything on one device first)
            light = _pad_ranges(light, n_mesh_dev, u_chunk)
            if heavy is not None:
                heavy = _pad_ranges(heavy, n_mesh_dev, _HEAVY_RANGE)
            return light, heavy, counts.astype(np.float32)
        light_dev = tuple(jax.device_put(x) for x in light)
        heavy_dev = (tuple(jax.device_put(x) for x in heavy)
                     if heavy is not None else None)
        return light_dev, heavy_dev, counts.astype(np.float32)

    p_light, p_heavy, n_i = partition_put(pu, pi)
    self_flags = tuple(deduped[name] is None for name in names)
    sec_light, sec_heavy, n_js = [], [], []
    for name in names:
        pair = deduped[name]
        if pair is None:
            n_js.append(n_i)
            continue
        su, si = pair
        sl, sh, cnt = partition_put(su, si)
        sec_light.append(sl)
        if n_heavy:
            sec_heavy.append(sh)
        n_js.append(cnt)
    k = min(max_correlators, n_items)
    block = min(item_block, n_items)
    los = list(range(0, n_items, block))
    lo_effs_np = np.array([min(lo, n_items - block) for lo in los], np.int32)

    if n_mesh_dev > 1:
        outs = _full_cco_topk_multi_sharded(
            p_light, tuple(sec_light),
            p_heavy if p_heavy is not None else (),
            tuple(sec_heavy) if n_heavy else (),
            jnp.asarray(lo_effs_np), jnp.asarray(n_i),
            jnp.asarray(np.stack(n_js)), jnp.float32(n_users),
            mesh=mesh, n_items=n_items, u_chunk=u_chunk,
            h_chunk=_HEAVY_RANGE, block=block, k=k,
            llr_threshold=llr_threshold, self_flags=self_flags)
    else:
        outs = _full_cco_topk_multi(
            p_light, tuple(sec_light),
            p_heavy, tuple(sec_heavy) if n_heavy else (),
            jnp.asarray(lo_effs_np), jnp.asarray(n_i),
            jnp.asarray(np.stack(n_js)), jnp.float32(n_users),
            n_items=n_items, u_chunk=u_chunk, h_chunk=_HEAVY_RANGE,
            block=block, k=k, llr_threshold=llr_threshold,
            self_flags=self_flags)
    outs = jax.device_get(outs)
    return {
        name: _gather_indicators(ss, ixs, los, lo_effs_np, block, n_items)
        for name, (ss, ixs) in zip(names, outs)
    }


@functools.partial(jax.jit, static_argnames=("k",))
def _score_history(idx, score, membership, boost, k: int):
    """score_i = Σ_slots score[i,s]·membership[idx[i,s]] (gather+dot) —
    the ES similarity query replacement. membership: [I] 0/1 vector of the
    user's history for this event type."""
    m = jnp.where(idx >= 0, membership[jnp.maximum(idx, 0)], 0.0)
    s = jnp.einsum("ik,ik->i", score, m) * boost
    return s


def score_user(
    indicator_list: list[tuple[Indicators, np.ndarray, float]],
    k: int,
    exclude: Optional[np.ndarray] = None,
    item_boost: Optional[np.ndarray] = None,
):
    """Combine per-event-type indicator scores for one user's history.

    indicator_list: [(indicators, membership [I] f32, boost)] per event
    type. ``item_boost`` [I] multiplies scores BEFORE top-k so boosted
    items can enter the result set. Returns (scores[k], idx[k]) host
    arrays.
    """
    total = None
    for ind, membership, boost in indicator_list:
        s = _score_history(
            jnp.asarray(ind.idx), jnp.asarray(ind.score),
            jnp.asarray(membership), jnp.float32(boost), ind.idx.shape[1],
        )
        total = s if total is None else total + s
    if item_boost is not None:
        total = total * jnp.asarray(item_boost, total.dtype)
    if exclude is not None:
        total = jnp.where(jnp.asarray(exclude), -jnp.inf, total)
    kk = min(k, total.shape[0])
    out = jax.lax.top_k(total, kk)
    return jax.device_get(out)
