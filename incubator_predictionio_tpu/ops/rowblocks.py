"""Length-bucketed row layout: ragged ratings → gather-minimal dense slabs.

The ALS half-step is gather-bound on TPU: the factor-row gather unit
sustains a fixed ~420M rows/s regardless of row width (≤128 lanes),
sortedness, or table size (measured in tools/profile_als.py; see
BASELINE.md "roofline"). Every padded entry is therefore a wasted gather
slot, and any segment-reduction after the gather is pure overhead. This
layout minimizes both:

- Each row's entries live in ONE dense slab row [C_b] whose capacity C_b
  comes from a geometric ladder of 8-multiples (~1.15 steps), so padding
  is ~5% instead of the ~11%+ of uniform tiling — and the per-row normal
  equations fall straight out of a [R_b, C_b, k] einsum with NO tile→row
  segment reduction at all (the reduction IS the einsum contraction).
- Rows longer than ``overflow_len`` split into full-width *virtual* rows
  plus a ladder remainder; virtual grams merge into their parent row with
  one tiny scatter-add (a few thousand rows at ML-20M scale).

Storage order ("π space"): solved-side factor rows live at *slots* laid
out shard-major over the mesh data axis, bucket-major within a shard,
ascending row id within a bucket (then filler slots). Contiguous
slot-blocks shard cleanly over both the data axis (solves) and the model
axis (ALX factor sharding) — MODEL_AXIS ownership windows are windows of
slots, so the two compose with no extra machinery. Column indices are
pre-mapped into the counterpart's π space on the host.

The layout is a pure function of the per-row nnz counts (``plan_layout``),
so multi-host processes agree on the full plan from one tiny allgather of
counts — then each fills only its own shards (``fill_buckets``).

The reference has no analog: its ALS data layout is MLlib's in/out-block
RDD partitioning inside Spark (SURVEY.md §2.9 model-parallel row).
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: Rows longer than this split into full-width virtual rows. 2048 keeps
#: the virtual-row scatter tiny (~2k rows at ML-20M) while bounding the
#: largest einsum slab.
OVERFLOW_LEN = 2048

#: Default geometric growth of the capacity ladder past 64. Every padded
#: slot is a wasted gather (the ALS wall), so tighter is faster until the
#: bucket count (= separate einsum programs inside the one jit) hurts
#: compile time. Measured at ML-20M shape: 1.15 → mean padding 1.100
#: (5+15 buckets), 1.05 → 1.052 (12+37 buckets) — ~4.6% fewer gathered
#: rows.  The r4 driver-verified A/B on the real chip ran 1.05 at
#: 18.67M ev/s vs 1.15 at 17.56M (+6.3% end-to-end, compile time
#: within noise), so 1.05 is the shipped default.
DEFAULT_LADDER_GROWTH = 1.05


def ladder_growth() -> float:
    """Effective ladder growth: PIO_ALS_LADDER_GROWTH env or the default.

    Parsed lazily so a malformed value degrades to the default with a
    warning instead of raising at import time in every entry point.
    Values outside (1.0, 4.0] also fall back to the default with a
    warning (≤1.0 never terminates the ladder; >4.0 is effectively a
    two-bucket ladder, certainly a typo).
    The value shapes the GLOBAL layout plan, so multi-host runs fold it
    into the layout fingerprint and allgather-verify agreement (see
    ops/als.py) — a cross-host mismatch fails fast instead of hanging in
    shape-mismatched collectives.
    """
    import warnings

    from ..common import envknobs

    g = envknobs.env_float("PIO_ALS_LADDER_GROWTH", DEFAULT_LADDER_GROWTH,
                           warn=True)
    if g == DEFAULT_LADDER_GROWTH:
        return DEFAULT_LADDER_GROWTH
    if not 1.0 < g <= 4.0:
        warnings.warn(
            f"PIO_ALS_LADDER_GROWTH={g} outside (1.0, 4.0]; using "
            f"{DEFAULT_LADDER_GROWTH}", stacklevel=2)
        return DEFAULT_LADDER_GROWTH
    return g


def length_ladder(max_len: int, overflow_len: int = OVERFLOW_LEN,
                  growth: float | None = None) -> np.ndarray:
    """Row-capacity ladder: multiples of 8 up to 64, then ~×growth steps
    (rounded up to a multiple of 8), capped at ``overflow_len``.

    Geometric steps bound per-row padding waste while keeping the bucket
    count (= separate einsum programs) in the tens. All hosts of a
    multi-host run must agree on ``growth`` (it shapes the global plan).
    """
    g = ladder_growth() if growth is None else float(growth)
    target = max(8, min(int(max_len), overflow_len))
    caps = []
    v = 0
    while v < target:
        if v < 64:
            v += 8
        else:
            v = min(max(-(-int(v * g) // 8) * 8, v + 8), overflow_len)
        caps.append(v)
    return np.asarray(caps, dtype=np.int64)


@dataclasses.dataclass(frozen=True)
class LayoutPlan:
    """Deterministic bucket layout derived from per-row counts alone."""

    lengths: np.ndarray        # [n_buckets] int64 — slab width per bucket
    bucket_rows: np.ndarray    # [n_buckets] int64 — rows per SHARD per bucket
    rows_per_shard: int        # Σ bucket_rows (incl. m-divisibility filler)
    n_shards: int
    n_rows: int                # logical rows
    overflow_len: int
    slot_of_row: np.ndarray    # [n_rows] int64 — global storage slot
    counts_slot: np.ndarray    # [n_shards*rows_per_shard] int64 (filler=0)
    bucket_of_row: np.ndarray  # [n_rows] int64
    # overflow bookkeeping (all-empty when no row exceeds overflow_len):
    v_rows_per_shard: int      # virtual rows per shard (max, padded)
    v_chunks_of_row: np.ndarray  # [n_rows] int64 — # full-width chunks
    v_base_of_row: np.ndarray  # [n_rows] int64 — row's first LOCAL v-slot
    v_parent: np.ndarray       # [n_shards*v_rows_per_shard] int64 LOCAL slot

    @property
    def has_heavy_bucket(self) -> bool:
        """True → the LAST bucket holds exactly the overflow parents
        (their grams are materialized + merged with the virtual slabs;
        all other buckets fuse ridge+solve per chunk). Derived, not
        stored: plan_layout routes heavy rows there iff any exist."""
        return self.v_rows_per_shard > 0

    @property
    def total_slots(self) -> int:
        return self.n_shards * self.rows_per_shard

    def shard_of_row(self, row: np.ndarray) -> np.ndarray:
        rpl = -(-self.n_rows // self.n_shards)
        return np.minimum(np.asarray(row) // rpl, self.n_shards - 1)


def plan_layout(counts: np.ndarray, n_shards: int, m_div: int = 1,
                overflow_len: int = OVERFLOW_LEN) -> LayoutPlan:
    """Plan the bucket layout for one side from its per-row nnz counts.

    Rows are owned by shards in contiguous logical ranges of
    ``ceil(n_rows / n_shards)`` (the multi-host range-read contract,
    ops.als.process_row_ranges). ``m_div``: rows_per_shard is rounded up
    so the total padded row count divides the model axis.
    """
    counts = np.asarray(counts, dtype=np.int64)
    n_rows = counts.shape[0]
    S = int(n_shards)
    rpl = -(-n_rows // S)  # logical rows per shard (last shard may be short)
    row_ids = np.arange(n_rows, dtype=np.int64)
    shard_of_row = np.minimum(row_ids // rpl, S - 1)

    # overflow split: full-width virtual chunks + a non-empty remainder
    v_chunks = np.where(counts > overflow_len, counts // overflow_len, 0)
    rem = counts - v_chunks * overflow_len
    fix = (v_chunks > 0) & (rem == 0)
    v_chunks[fix] -= 1
    rem[fix] = overflow_len

    ladder = length_ladder(int(rem.max()) if n_rows else 8, overflow_len)
    bucket_of_row = np.searchsorted(ladder, np.maximum(rem, 1))
    n_buckets = len(ladder)
    # Rows with overflow (virtual) chunks go to a DEDICATED LAST bucket:
    # their normal equations need the virtual scatter-add before the
    # solve, so the device loop materializes grams only for this (small)
    # bucket and fuses ridge+solve per chunk everywhere else — the
    # full [rows, k, k] materialization would be ~11 GB at ML-20M
    # rank 128.
    heavy_mask = v_chunks > 0
    if heavy_mask.any():
        heavy_cap = ladder[np.searchsorted(
            ladder, max(int(rem[heavy_mask].max()), 1))]
        bucket_of_row = np.where(heavy_mask, n_buckets, bucket_of_row)
        ladder = np.append(ladder, heavy_cap)
        n_buckets += 1

    per_sb = np.bincount(
        shard_of_row * n_buckets + bucket_of_row, minlength=S * n_buckets
    ).reshape(S, n_buckets)
    bucket_rows = per_sb.max(axis=0)

    # drop empty buckets, keep bucket 0 (filler target) if ladder nonempty
    keep = np.nonzero(bucket_rows > 0)[0]
    if keep.size == 0:
        keep = np.array([0])
    new_idx = np.full(n_buckets, -1, dtype=np.int64)
    new_idx[keep] = np.arange(keep.size)
    lengths = ladder[keep]
    bucket_rows = bucket_rows[keep].astype(np.int64)
    bucket_of_row = new_idx[bucket_of_row]
    per_sb = per_sb[:, keep]
    n_buckets = keep.size

    rows_per_shard = int(bucket_rows.sum())
    pad_m = (-rows_per_shard) % int(m_div)
    if rows_per_shard + pad_m < 1:
        pad_m = 1
    bucket_rows[0] += pad_m  # filler rows take the cheapest slab width
    rows_per_shard += pad_m

    # slot of each row: shard-major, bucket blocks, rank within bucket
    bucket_base = np.zeros(n_buckets + 1, dtype=np.int64)
    np.cumsum(bucket_rows, out=bucket_base[1:])
    order = np.lexsort((row_ids, bucket_of_row, shard_of_row))
    sb_sorted = (shard_of_row * n_buckets + bucket_of_row)[order]
    group_start = np.zeros(len(order), dtype=np.int64)
    if len(order):
        new_group = np.empty(len(order), dtype=bool)
        new_group[0] = True
        new_group[1:] = sb_sorted[1:] != sb_sorted[:-1]
        starts = np.nonzero(new_group)[0]
        group_start = starts[np.cumsum(new_group) - 1]
    rank = np.arange(len(order), dtype=np.int64) - group_start
    slot_sorted = (
        shard_of_row[order] * rows_per_shard
        + bucket_base[bucket_of_row[order]]
        + rank
    )
    slot_of_row = np.empty(n_rows, dtype=np.int64)
    slot_of_row[order] = slot_sorted

    counts_slot = np.zeros(S * rows_per_shard, dtype=np.int64)
    counts_slot[slot_of_row] = counts

    # virtual rows: grouped per shard, ordered by (row, chunk)
    v_per_shard_real = np.bincount(
        shard_of_row, weights=v_chunks.astype(np.float64), minlength=S
    ).astype(np.int64)
    Rv = int(v_per_shard_real.max()) if n_rows else 0
    v_base_of_row = np.zeros(n_rows, dtype=np.int64)
    v_parent = np.zeros(S * Rv, dtype=np.int64)
    if Rv:
        # local v-slot base per row: running sum of v_chunks within shard
        order_r = row_ids  # rows already ascending == (shard, row) order
        cum = np.cumsum(v_chunks[order_r])
        shard_cum_start = np.zeros(n_rows, dtype=np.int64)
        # subtract the cumulative total of previous shards
        shard_first = np.searchsorted(shard_of_row, np.arange(S))
        prev_total = np.zeros(S, dtype=np.int64)
        for s in range(1, S):
            prev_total[s] = cum[shard_first[s] - 1] if shard_first[s] > 0 else 0
        shard_cum_start = prev_total[shard_of_row]
        v_base_of_row = cum - v_chunks - shard_cum_start
        heavy = np.nonzero(v_chunks > 0)[0]
        for r in heavy:  # heavy rows are few by construction
            s = shard_of_row[r]
            base = s * Rv + v_base_of_row[r]
            v_parent[base:base + v_chunks[r]] = (
                slot_of_row[r] - s * rows_per_shard
            )
    return LayoutPlan(
        lengths=lengths,
        bucket_rows=bucket_rows,
        rows_per_shard=rows_per_shard,
        n_shards=S,
        n_rows=n_rows,
        overflow_len=overflow_len,
        slot_of_row=slot_of_row,
        counts_slot=counts_slot,
        bucket_of_row=bucket_of_row,
        v_rows_per_shard=Rv,
        v_chunks_of_row=v_chunks,
        v_base_of_row=v_base_of_row,
        v_parent=v_parent,
    )


def plan_and_fill_both(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rating: np.ndarray,
    n_users: int,
    n_items: int,
    n_shards: int,
    m_div: int = 1,
    fill_vals: bool = True,
    parallel: bool | None = None,
):
    """Plan and fill BOTH sides' slabs for an ALS train:
    ``(plan_u, plan_i, arrs_u, arrs_i)``.

    The two sides' plans (and then their fills) are independent host
    passes over the same COO triple, and both the native single-pass
    scatter (a ctypes call) and the numpy fallback's radix argsort
    release the GIL — so with ``parallel`` (default: on unless
    PIO_PIPELINE=off) each pair runs on input-pipeline worker threads,
    overlapping the dominant host cost of ALS layout prep. Results are
    identical to the serial path: nothing is shared but read-only
    inputs.
    """
    if parallel is None:
        from ..workflow.input_pipeline import PipelineConfig

        parallel = PipelineConfig.from_env().mode != "off"

    counts_u = np.bincount(np.asarray(user_idx, np.int64), minlength=n_users)
    counts_i = np.bincount(np.asarray(item_idx, np.int64), minlength=n_items)

    def _run(*thunks):
        if parallel:
            from ..workflow.input_pipeline import host_parallel

            return host_parallel(*thunks)
        return [t() for t in thunks]

    plan_u, plan_i = _run(
        lambda: plan_layout(counts_u, n_shards, m_div=m_div),
        lambda: plan_layout(counts_i, n_shards, m_div=m_div),
    )
    arrs_u, arrs_i = _run(
        lambda: fill_buckets(plan_u, user_idx, item_idx, rating,
                             col_slot_map=plan_i.slot_of_row,
                             sentinel=plan_i.total_slots,
                             fill_vals=fill_vals),
        lambda: fill_buckets(plan_i, item_idx, user_idx, rating,
                             col_slot_map=plan_u.slot_of_row,
                             sentinel=plan_u.total_slots,
                             fill_vals=fill_vals),
    )
    return plan_u, plan_i, arrs_u, arrs_i


@dataclasses.dataclass(frozen=True)
class BucketArrays:
    """Dense per-bucket entry slabs for a contiguous range of shards.

    cols hold COUNTERPART π-space slot indices; padding slots hold the
    sentinel (= counterpart total padded rows: a zero factor row in
    replicated mode, outside every ownership window in sharded mode).
    """

    cols: tuple[np.ndarray, ...]   # per bucket [S_loc*R_b, C_b] int32
    vals: tuple[np.ndarray, ...]   # per bucket [S_loc*R_b, C_b] f32
    v_cols: np.ndarray             # [S_loc*Rv, overflow_len] int32
    v_vals: np.ndarray             # [S_loc*Rv, overflow_len] f32
    shard0: int
    n_local_shards: int
    # fill_vals=False (binary-ratings mode): vals is an empty tuple and
    # v_vals a zero-size array — the device synthesizes exact ones.


def fill_buckets(plan: LayoutPlan, row: np.ndarray, col: np.ndarray,
                 val: np.ndarray, col_slot_map: np.ndarray, sentinel: int,
                 shard0: int = 0, n_local_shards: int | None = None,
                 use_native: bool | None = None,
                 fill_vals: bool = True) -> BucketArrays:
    """Scatter entries into the planned slabs for shards
    [shard0, shard0+n_local_shards). ``row`` must contain ONLY rows owned
    by those shards (the multi-host range-read contract); ``col`` is
    global counterpart row ids, mapped through ``col_slot_map`` into the
    counterpart's π space.

    ``use_native``: None = auto (the C++ single-pass scatter when the
    toolchain is available — it replaces the numpy path's stable argsort,
    the dominant host cost of layout prep, and is bit-identical to it);
    False forces the numpy path (tests use both and assert equality).

    ``fill_vals=False`` (binary-ratings mode): the value slabs are
    neither allocated nor filled — every real entry is 1.0 and the
    device synthesizes exact ones (ops/als.py binary_ratings).
    """
    S_loc = plan.n_shards - shard0 if n_local_shards is None else int(n_local_shards)
    if fill_vals:
        val = np.asarray(val, dtype=np.float32)
    n_buckets = len(plan.lengths)
    Rv, OV = plan.v_rows_per_shard, plan.overflow_len

    # flat buffer: [bucket slabs ..., virtual slab]
    sizes = [S_loc * int(plan.bucket_rows[b]) * int(plan.lengths[b])
             for b in range(n_buckets)]
    v_size = S_loc * Rv * OV
    offsets = np.zeros(n_buckets + 2, dtype=np.int64)
    np.cumsum(np.asarray(sizes + [v_size], dtype=np.int64), out=offsets[1:])
    flat_cols = np.full(int(offsets[-1]), sentinel, dtype=np.int32)
    flat_vals = (np.zeros(int(offsets[-1]), dtype=np.float32)
                 if fill_vals else None)

    if len(row):
        if plan.n_rows > 2**31 - 1:
            raise NotImplementedError(
                "fill_buckets: row ids beyond int32 are not supported")
        n_rows = plan.n_rows
        shard_r = plan.shard_of_row(np.arange(n_rows, dtype=np.int64))
        # per-row flat bases (garbage for non-local rows — the range
        # check below guarantees none are referenced)
        bucket_base = np.zeros(n_buckets + 1, dtype=np.int64)
        np.cumsum(plan.bucket_rows, out=bucket_base[1:])
        b_r = plan.bucket_of_row
        rib = (plan.slot_of_row - shard_r * plan.rows_per_shard
               - bucket_base[b_r])
        prim_base = (offsets[b_r]
                     + ((shard_r - shard0) * plan.bucket_rows[b_r] + rib)
                     * plan.lengths[b_r])
        vc_r = plan.v_chunks_of_row
        # a row's virtual chunks are CONSECUTIVE v-slots, so its first
        # vc*OV entries land contiguously at v_base + pos
        v_base = (offsets[n_buckets]
                  + ((shard_r - shard0) * Rv + plan.v_base_of_row) * OV)

        # range checks before any gather of the per-row tables above
        # (also keeps the native and numpy paths raising identically)
        row64 = np.asarray(row, np.int64)
        s_lo, s_hi = (int(s) for s in plan.shard_of_row(
            np.array([row64.min(), row64.max()], np.int64)))
        if s_lo < shard0 or s_hi >= shard0 + S_loc:
            raise ValueError(
                "fill_buckets: entries reference rows outside shards "
                f"[{shard0}, {shard0 + S_loc}) — range-read only owned rows")
        col64 = np.asarray(col, np.int64)
        if len(col64) and (col64.min() < 0
                           or col64.max() >= len(col_slot_map)):
            raise ValueError(
                "fill_buckets: column ids outside the counterpart slot map")

        done = False
        if use_native is not False:
            # C++ single-pass scatter (native/src/event_codec.cc
            # pio_fill_entries): per-row write cursors replace the
            # argsort + position arithmetic below; same entry order.
            try:
                from ..native import NativeUnavailable, fill_entries
                fill_entries(row64, col64, val if fill_vals else None,
                             col_slot_map, prim_base, v_base, vc_r * OV,
                             flat_cols, flat_vals)
                done = True
            except NativeUnavailable:
                if use_native is True:
                    raise
        if not done:
            # numpy fallback: one int32 stable argsort (radix — 2x+
            # faster than int64 comparison sort; row ids bounded by the
            # int32 guard above), then only gathers of small per-ROW
            # tables + one scatter.
            order = np.argsort(np.asarray(row, np.int32), kind="stable")
            rs = row64[order]
            # remap columns into counterpart pi space at the SOURCE (all
            # real); sentinel prefill covers the padding slots.
            cs = np.asarray(col_slot_map, np.int64)[
                col64[order]].astype(np.int32)

            # position of each entry within its row (stable original order)
            rmin = int(rs[0])
            cnt = np.bincount((rs - rmin).astype(np.int64))
            starts = np.zeros(len(cnt), dtype=np.int64)
            np.cumsum(cnt[:-1], out=starts[1:])
            pos = np.arange(len(rs), dtype=np.int64) - starts[rs - rmin]

            vc_e = vc_r[rs] * OV
            dest = np.where(pos < vc_e,
                            v_base[rs] + pos,
                            prim_base[rs] + pos - vc_e)
            flat_cols[dest] = cs
            if fill_vals:
                flat_vals[dest] = val[order]

    cols, vals = [], []
    for b in range(n_buckets):
        R, C = S_loc * int(plan.bucket_rows[b]), int(plan.lengths[b])
        cols.append(flat_cols[offsets[b]:offsets[b + 1]].reshape(R, C))
        if fill_vals:
            vals.append(flat_vals[offsets[b]:offsets[b + 1]].reshape(R, C))
    v_cols = flat_cols[offsets[n_buckets]:offsets[n_buckets + 1]].reshape(
        S_loc * Rv, OV)
    v_vals = (flat_vals[offsets[n_buckets]:offsets[n_buckets + 1]].reshape(
        S_loc * Rv, OV) if fill_vals
        else np.zeros((0, OV), np.float32))
    return BucketArrays(
        cols=tuple(cols), vals=tuple(vals), v_cols=v_cols, v_vals=v_vals,
        shard0=shard0, n_local_shards=S_loc,
    )
