"""Top-k scoring kernels for serving (the `recommendProducts` hot path).

Reference behaviour: MLlib MatrixFactorizationModel.recommendProducts —
driver-side BLAS dot products + sort (SURVEY.md §3.2 hot path). TPU-native:
one fused matvec + lax.top_k per query, jitted once per (model-shape, k);
the engine server calls the cached executable so per-query Python work is
JSON parsing only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_scores(user_vec, item_factors, exclude_mask, k: int):
    scores = item_factors @ user_vec  # [n_items]
    scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def top_k_items(user_vec, item_factors, k: int, exclude=None):
    """Returns (scores[k], indices[k]) as host numpy arrays.

    ``exclude``: optional bool mask [n_items] of items to suppress
    (seen-item filtering for the e-commerce template).
    """
    n_items = item_factors.shape[0]
    if exclude is None:
        exclude = jnp.zeros((n_items,), dtype=bool)
    k = min(int(k), n_items)
    out = _topk_scores(
        jnp.asarray(user_vec), jnp.asarray(item_factors), jnp.asarray(exclude), k
    )
    # Single host transfer: through a remote-PJRT tunnel each device_get is
    # a round-trip, so fetching (scores, idx) together halves query latency.
    return jax.device_get(out)


@functools.partial(jax.jit, static_argnames=("k",))
def _batch_topk(user_vecs, item_factors, k: int):
    scores = user_vecs @ item_factors.T  # [b, n_items]
    return jax.lax.top_k(scores, k)


def batch_top_k(user_vecs, item_factors, k: int):
    """Vectorized top-k for batch_predict/eval sweeps."""
    k = min(int(k), item_factors.shape[0])
    return jax.device_get(
        _batch_topk(jnp.asarray(user_vecs), jnp.asarray(item_factors), k)
    )


@functools.partial(jax.jit, static_argnames=("k",))
def _item_sim_topk(query_vecs, item_factors, exclude_mask, k: int):
    """Cosine similarity of query items against the catalog, summed over
    query items (similar-product semantics)."""
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=1, keepdims=True) + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=1, keepdims=True) + 1e-9)
    scores = (fn @ qn.T).sum(axis=1)  # [n_items]
    scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def similar_items(query_vecs, item_factors, k: int, exclude=None):
    n_items = item_factors.shape[0]
    if exclude is None:
        exclude = jnp.zeros((n_items,), dtype=bool)
    k = min(int(k), n_items)
    return jax.device_get(
        _item_sim_topk(
            jnp.asarray(query_vecs), jnp.asarray(item_factors), jnp.asarray(exclude), k
        )
    )
