"""Top-k scoring kernels for serving (the `recommendProducts` hot path).

Reference behaviour: MLlib MatrixFactorizationModel.recommendProducts —
driver-side BLAS dot products + sort (SURVEY.md §3.2 hot path). TPU-native:
one fused matvec + lax.top_k per query, jitted once per (model-shape, k);
the engine server calls the cached executable so per-query Python work is
JSON parsing only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_scores(user_vec, item_factors, exclude_mask, k: int):
    # mul+reduce instead of a gemv: the reduction tree over rank is then
    # independent of the row count, so a MODEL_AXIS-sharded catalog
    # (ops/sharded_topk.py) produces bitwise-identical scores. A gemv's
    # row-block tail handling varies with n_items — measured 1-ULP
    # differences on row slices. Cost: none; the serving matvec is
    # HBM-bandwidth-bound on reading the catalog either way.
    scores = (item_factors * user_vec[None, :]).sum(axis=1)  # [n_items]
    scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


@functools.lru_cache(maxsize=None)
def _no_exclude_mask(n_items: int):
    """Device-resident all-False mask, one per catalog size. Building
    `jnp.zeros((n_items,), bool)` per query cost ~0.2 ms of eager
    dispatch + transfer on the CPU-local hot path (ISSUE 17 profile) —
    for a mask that never changes. Same jit cache key, same executable,
    so answers stay bitwise identical."""
    return jax.device_put(np.zeros((n_items,), dtype=bool))


def top_k_items(user_vec, item_factors, k: int, exclude=None):
    """Returns (scores[k], indices[k]) as host numpy arrays.

    ``exclude``: optional bool mask [n_items] of items to suppress
    (seen-item filtering for the e-commerce template).
    """
    n_items = item_factors.shape[0]
    if exclude is None:
        exclude = _no_exclude_mask(n_items)
    k = min(int(k), n_items)
    # arguments go to the jitted kernel RAW: jit's C++ dispatch commits
    # them to device far cheaper than eager jnp.asarray per query
    # (measured ~0.4 ms/query of lax_numpy/bind machinery saved)
    out = _topk_scores(user_vec, item_factors, exclude, k)
    # Single host transfer: through a remote-PJRT tunnel each device_get is
    # a round-trip, so fetching (scores, idx) together halves query latency.
    return jax.device_get(out)


@functools.partial(jax.jit, static_argnames=("k",))
def _batch_topk(user_vecs, item_factors, k: int):
    scores = user_vecs @ item_factors.T  # [b, n_items]
    return jax.lax.top_k(scores, k)


def bucket_k(k: int, n_total: int) -> int:
    """Pow2 (≥8) k buckets so clients varying "num" share executables.
    Shared by the single-device and sharded (ops/sharded_topk.py) paths —
    the sharded bit-identity guarantee depends on both bucketing alike."""
    return min(max(8, 1 << max(k - 1, 0).bit_length()), n_total)


def pad_batch_pow2(user_vecs: np.ndarray) -> np.ndarray:
    """Pad the batch dim to the next power of two (serving batches vary
    per micro-batch window; unpadded shapes would compile one executable
    per distinct size). Batches >256 pass through: eval / `pio
    batchpredict` call once with thousands of fixed-size queries — one
    compile either way, and pow2 padding there would waste up to 2x the
    matmul. (EngineServer caps its micro-batch max_batch at 256 to match.)"""
    b = user_vecs.shape[0]
    bp = (1 << max(b - 1, 0).bit_length()) if b <= 256 else b
    if bp == b:
        return user_vecs
    return np.concatenate(
        [user_vecs,
         np.zeros((bp - b,) + user_vecs.shape[1:], user_vecs.dtype)],
        axis=0)


def batch_top_k(user_vecs, item_factors, k: int):
    """Vectorized top-k for batch_predict/eval sweeps and the serving
    micro-batch path. The batch dim is padded to the next power of two:
    serving batches vary in size per window, and an unpadded shape would
    compile a fresh executable per distinct size (~1s each — measured
    1.5s p99 spikes through the remote tunnel)."""
    user_vecs = np.asarray(user_vecs)
    k = min(int(k), item_factors.shape[0])
    b = user_vecs.shape[0]
    # k is a static jit arg too: bucketed so clients varying "num" share
    # executables per bucket instead of compiling one per distinct value.
    kp = bucket_k(k, item_factors.shape[0])
    user_vecs = pad_batch_pow2(user_vecs)
    scores, idx = jax.device_get(_batch_topk(user_vecs, item_factors, kp))
    return scores[:b, :k], idx[:b, :k]


def normalize_rows(x) -> np.ndarray:
    """Row-normalize a factor matrix on the host (float32). Done ONCE at
    deploy/warm-up time: per-query catalog normalization was O(N·rank)
    wasted work, and device-side norm reductions vary bitwise with the
    row count at small shapes, which would break the sharded-catalog
    bit-identity guarantee (ops/sharded_topk.py)."""
    x = np.asarray(x, np.float32)
    return x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-9)


def similar_items(query_vecs, item_factors_normed, k: int, exclude=None):
    """Summed cosine similarity of query items against the catalog
    (similar-product semantics). ``item_factors_normed`` must be
    row-normalized (normalize_rows) — model caches do this once.

    sum_q dot(f, qn_q) == dot(f, sum_q qn_q): the query vectors fold
    into one, so this is exactly the top_k_items matvec — one kernel,
    shared executables, and bitwise parity with the sharded path."""
    qn = normalize_rows(np.atleast_2d(np.asarray(query_vecs, np.float32)))
    return top_k_items(qn.sum(axis=0), item_factors_normed, k, exclude=exclude)
