"""Top-k scoring kernels for serving (the `recommendProducts` hot path).

Reference behaviour: MLlib MatrixFactorizationModel.recommendProducts —
driver-side BLAS dot products + sort (SURVEY.md §3.2 hot path). TPU-native:
one fused matvec + lax.top_k per query, jitted once per (model-shape, k);
the engine server calls the cached executable so per-query Python work is
JSON parsing only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_scores(user_vec, item_factors, exclude_mask, k: int):
    scores = item_factors @ user_vec  # [n_items]
    scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def top_k_items(user_vec, item_factors, k: int, exclude=None):
    """Returns (scores[k], indices[k]) as host numpy arrays.

    ``exclude``: optional bool mask [n_items] of items to suppress
    (seen-item filtering for the e-commerce template).
    """
    n_items = item_factors.shape[0]
    if exclude is None:
        exclude = jnp.zeros((n_items,), dtype=bool)
    k = min(int(k), n_items)
    out = _topk_scores(
        jnp.asarray(user_vec), jnp.asarray(item_factors), jnp.asarray(exclude), k
    )
    # Single host transfer: through a remote-PJRT tunnel each device_get is
    # a round-trip, so fetching (scores, idx) together halves query latency.
    return jax.device_get(out)


@functools.partial(jax.jit, static_argnames=("k",))
def _batch_topk(user_vecs, item_factors, k: int):
    scores = user_vecs @ item_factors.T  # [b, n_items]
    return jax.lax.top_k(scores, k)


def batch_top_k(user_vecs, item_factors, k: int):
    """Vectorized top-k for batch_predict/eval sweeps and the serving
    micro-batch path. The batch dim is padded to the next power of two:
    serving batches vary in size per window, and an unpadded shape would
    compile a fresh executable per distinct size (~1s each — measured
    1.5s p99 spikes through the remote tunnel)."""
    user_vecs = np.asarray(user_vecs)
    k = min(int(k), item_factors.shape[0])
    b = user_vecs.shape[0]
    # Pad only serving-scale batches: eval / `pio batchpredict` call this
    # once with thousands of fixed-size queries — one compile either way,
    # and pow2 padding there would waste up to 2x the matmul.
    # (EngineServer caps its micro-batch max_batch at 256 to match.)
    bp = (1 << max(b - 1, 0).bit_length()) if b <= 256 else b
    # k is a static jit arg too: bucket it to the next pow2 (≥8) so
    # clients varying "num" share executables per bucket instead of
    # compiling one per distinct value.
    kp = min(max(8, 1 << max(k - 1, 0).bit_length()), item_factors.shape[0])
    if bp != b:
        user_vecs = np.concatenate(
            [user_vecs, np.zeros((bp - b,) + user_vecs.shape[1:],
                                 user_vecs.dtype)], axis=0)
    scores, idx = jax.device_get(
        _batch_topk(jnp.asarray(user_vecs), jnp.asarray(item_factors), kp)
    )
    return scores[:b, :k], idx[:b, :k]


@functools.partial(jax.jit, static_argnames=("k",))
def _item_sim_topk(query_vecs, item_factors, exclude_mask, k: int):
    """Cosine similarity of query items against the catalog, summed over
    query items (similar-product semantics)."""
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=1, keepdims=True) + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=1, keepdims=True) + 1e-9)
    scores = (fn @ qn.T).sum(axis=1)  # [n_items]
    scores = jnp.where(exclude_mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def similar_items(query_vecs, item_factors, k: int, exclude=None):
    n_items = item_factors.shape[0]
    if exclude is None:
        exclude = jnp.zeros((n_items,), dtype=bool)
    k = min(int(k), n_items)
    return jax.device_get(
        _item_sim_topk(
            jnp.asarray(query_vecs), jnp.asarray(item_factors), jnp.asarray(exclude), k
        )
    )
