"""ML helper lib (reference: e2/ — SURVEY.md §2.7)."""

from .cross_validation import k_fold_indices

__all__ = ["k_fold_indices"]
