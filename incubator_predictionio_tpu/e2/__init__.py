"""ML helper lib (reference: e2/ — SURVEY.md §2.7)."""

from .cross_validation import k_fold_indices
from .engine import BinaryVectorizer, CategoricalNaiveBayes, markov_chain

__all__ = [
    "BinaryVectorizer", "CategoricalNaiveBayes", "k_fold_indices",
    "markov_chain",
]
