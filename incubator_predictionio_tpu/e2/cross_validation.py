"""K-fold splitting (reference: e2/.../evaluation/CrossValidation.scala)."""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def k_fold_indices(
    n: int, k: int, seed: int = 0
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (train_mask, test_mask) boolean pairs for k folds."""
    rng = np.random.default_rng(seed)
    fold = rng.integers(0, k, n)
    for f in range(k):
        test = fold == f
        yield ~test, test
