"""e2.engine helpers (reference: e2/src/main/scala/.../e2/engine/
{CategoricalNaiveBayes,BinaryVectorizer,MarkovChain}.scala — small ML
utilities used by classification/text examples)."""

from __future__ import annotations

import dataclasses
import math
from collections import defaultdict
from typing import Hashable, Iterable, Mapping, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """NB over categorical string features (reference:
    CategoricalNaiveBayes.Model — priors + per-feature likelihood maps)."""

    log_priors: dict[str, float]
    # label → feature position → value → log likelihood
    log_likelihoods: dict[str, list[dict[str, float]]]
    default_log_likelihood: float

    def log_score(self, features: Sequence[str], label: str) -> Optional[float]:
        if label not in self.log_priors:
            return None
        ll = self.log_likelihoods[label]
        total = self.log_priors[label]
        for pos, value in enumerate(features):
            total += ll[pos].get(value, self.default_log_likelihood)
        return total

    def predict(self, features: Sequence[str]) -> str:
        return max(
            self.log_priors,
            key=lambda lab: self.log_score(features, lab),
        )


class CategoricalNaiveBayes:
    """Train from (label, [categorical features...]) points."""

    @staticmethod
    def train(
        points: Iterable[tuple[str, Sequence[str]]],
        default_log_likelihood: float = math.log(1e-9),
    ) -> CategoricalNaiveBayesModel:
        points = list(points)
        if not points:
            raise ValueError("no labeled points")
        n_positions = len(points[0][1])
        label_counts: dict[str, int] = defaultdict(int)
        value_counts: dict[str, list[dict[str, int]]] = {}
        for label, feats in points:
            label_counts[label] += 1
            if label not in value_counts:
                value_counts[label] = [defaultdict(int) for _ in range(n_positions)]
            for pos, v in enumerate(feats):
                value_counts[label][pos][v] += 1
        total = sum(label_counts.values())
        log_priors = {
            lab: math.log(c / total) for lab, c in label_counts.items()
        }
        log_likelihoods = {
            lab: [
                {v: math.log(c / label_counts[lab]) for v, c in pos_counts.items()}
                for pos_counts in value_counts[lab]
            ]
            for lab in label_counts
        }
        return CategoricalNaiveBayesModel(
            log_priors, log_likelihoods, default_log_likelihood
        )


class BinaryVectorizer:
    """Categorical (position, value) pairs → binary vectors (reference:
    e2.engine.BinaryVectorizer)."""

    def __init__(self, index: Mapping[tuple[int, str], int]):
        self.index = dict(index)

    @staticmethod
    def fit(points: Iterable[Sequence[str]]) -> "BinaryVectorizer":
        index: dict[tuple[int, str], int] = {}
        for feats in points:
            for pos, v in enumerate(feats):
                key = (pos, v)
                if key not in index:
                    index[key] = len(index)
        return BinaryVectorizer(index)

    @property
    def n_features(self) -> int:
        return len(self.index)

    def transform(self, feats: Sequence[str]) -> np.ndarray:
        x = np.zeros(len(self.index), np.float32)
        for pos, v in enumerate(feats):
            j = self.index.get((pos, v))
            if j is not None:
                x[j] = 1.0
        return x


def markov_chain(matrix_counts: np.ndarray, top_k: int) -> list[list[tuple[int, float]]]:
    """Row-normalized transition probabilities, top-k per state
    (reference: e2.engine.MarkovChain — sparse transition model)."""
    counts = np.asarray(matrix_counts, np.float64)
    out = []
    for row in counts:
        total = row.sum()
        if total <= 0:
            out.append([])
            continue
        probs = row / total
        idx = np.argsort(-probs)[:top_k]
        out.append([(int(j), float(probs[j])) for j in idx if probs[j] > 0])
    return out
