"""Benchmark: p50 `pio query` latency (BASELINE.json north star #2).

Runs the REAL serving path end to end: seed an ML-20M-shaped catalog into
the event store, `run_train` the recommendation engine (persisting the
model through the Models DAO), deploy it behind the actual EngineServer,
and measure `POST /queries.json` over HTTP — JSON parse, algorithm predict
(AOT-cached matvec + top-k on device), serving combine, JSON response —
the exact path a production client hits (reference hot path: SURVEY.md
§3.2: spray route → algo.predict → LServing.serve).

Prints ONE JSON line: {"metric": ..., "value": p50_ms, "unit": "ms",
"vs_baseline": 10/p50} (north star <10 ms ⇒ vs_baseline > 1).

Hardware-attachment note: this sandbox reaches the TPU through a
remote-PJRT tunnel with a ~65-70 ms per-dispatch round-trip (measured
below as dispatch_rtt_ms and reported alongside). The serving stack's own
overhead = http_p50 − dispatch_rtt; on a host-attached chip the dispatch
is sub-millisecond.

Concurrency mode (VERDICT r2 #7 — serving under load): set
PIO_QBENCH_QPS to ALSO run an open-loop load test — arrivals scheduled
at the target rate regardless of completions (the honest tail-latency
protocol; a closed loop hides queueing), async aiohttp clients,
reporting p50/p95/p99 + achieved throughput at each offered rate, with
the micro-batching window off and on (PIO_QBENCH_BATCH_MS, default 5).

Overload bracket (ISSUE 6 acceptance): unless PIO_QBENCH_OVERLOAD=0,
the run ALSO measures behavior at offered load ≫ capacity — a small
admission-gated server (conc 2 + pending 8) with an injected slow model
(PIO_FAULT_SPEC latency on query.predict) under an open-loop flood —
and persists goodput, shed rate and ACCEPTED-query p99 next to the QPS
numbers, plus whether sheds carried a jittered Retry-After. The honest
overload protocol: arrivals keep coming regardless of completions, so
an unbounded queue would show unbounded p99 here, not a hidden one.

Multi-tenant bracket (ISSUE 19): unless PIO_QBENCH_TENANTS=0, one
mux-armed EngineServer serves 1/8/32 apps in the SAME run with
PIO_QBENCH_TENANT_RESIDENT (default 6) resident models — resident-hit
vs cold-load p50/p99 per size (each query classified by the mux's own
coldLoads counter), eviction churn past the residency bound, and the
classic no-header path as the mux-overhead control; persisted as
BASELINE `measured_multitenant`.

Env: PIO_QBENCH_ITEMS (default 26744), PIO_QBENCH_RANK (32),
PIO_QBENCH_USERS (3000), PIO_QBENCH_N (200 queries),
PIO_QBENCH_QPS ("50,100,200"), PIO_QBENCH_DURATION (seconds per rate),
PIO_QBENCH_BATCH_MS (5), PIO_QBENCH_OVERLOAD (1),
PIO_QBENCH_TENANT_SIZES ("1,8,32"), PIO_BENCH_FORCE_CPU=1
to smoke off-TPU.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def load_test(base_url: str, qps: float, duration: float, n_users: int,
              seed: int = 1):
    """Open-loop fixed-rate load: one asyncio loop schedules arrivals at
    exact times; each request is an independent task. Returns latency
    percentiles + achieved rate + error count."""
    import asyncio

    import aiohttp

    async def run():
        rng = np.random.default_rng(seed)
        n = max(int(qps * duration), 1)
        lat, errors = [], [0]
        async with aiohttp.ClientSession() as sess:
            # warm the connection pool
            await sess.post(base_url + "/queries.json",
                            json={"user": "0", "num": 10})

            async def one(delay, user):
                await asyncio.sleep(delay)
                t0 = time.perf_counter()
                try:
                    async with sess.post(
                        base_url + "/queries.json",
                        json={"user": user, "num": 10},
                    ) as resp:
                        await resp.read()
                        if resp.status != 200:
                            errors[0] += 1
                            return
                except Exception:
                    errors[0] += 1
                    return
                lat.append((time.perf_counter() - t0) * 1000)

            start = time.perf_counter()
            tasks = [
                asyncio.create_task(
                    one(k / qps, str(int(rng.integers(0, n_users)))))
                for k in range(n)
            ]
            await asyncio.gather(*tasks)
            wall = time.perf_counter() - start
        return lat, errors[0], len(lat) / wall

    return asyncio.run(run())


def overload_bracket(engine, storage, n_users, *, conc=2, max_pending=8,
                     service_ms=50.0, overload_factor=4.0, duration=4.0):
    """Open-loop flood at offered load ≫ capacity against an
    admission-gated server with an injected slow model. Returns
    {goodput_qps, shed_rate, accepted_p99_ms, ...} — the numbers an
    operator sizes PIO_QUERY_* from."""
    import asyncio

    import aiohttp

    from incubator_predictionio_tpu.common import faultinject
    from incubator_predictionio_tpu.workflow.create_server import EngineServer
    from server_utils import ServerThread

    capacity = conc / (service_ms / 1000.0)
    offered = capacity * overload_factor
    prev_spec = os.environ.get("PIO_FAULT_SPEC")
    srv = EngineServer(
        engine, engine_factory_name="qbench", storage=storage,
        query_conc=conc, query_max_pending=max_pending,
        query_deadline_ms=30_000)
    # armed AFTER construction so warm-up queries don't consume counts
    os.environ["PIO_FAULT_SPEC"] = \
        f"query.predict:latency:100000000:{service_ms / 1000.0}"
    faultinject.reset()

    async def run(base):
        ok_lat, sheds, retry_afters, errors = [], [0], set(), [0]
        timeout = aiohttp.ClientTimeout(total=60)
        async with aiohttp.ClientSession(timeout=timeout) as sess:

            async def one(delay, user):
                await asyncio.sleep(delay)
                t0 = time.perf_counter()
                try:
                    async with sess.post(
                            base + "/queries.json",
                            json={"user": user, "num": 10}) as resp:
                        await resp.read()
                        if resp.status == 200:
                            ok_lat.append(
                                (time.perf_counter() - t0) * 1000)
                        elif resp.status == 503:
                            sheds[0] += 1
                            ra = resp.headers.get("Retry-After")
                            if ra is not None:
                                retry_afters.add(ra)
                        else:
                            errors[0] += 1
                except Exception:  # noqa: BLE001
                    errors[0] += 1

            n = int(offered * duration)
            t0 = time.perf_counter()
            await asyncio.gather(*[
                asyncio.create_task(one(k / offered, str(k % n_users)))
                for k in range(n)])
            wall = time.perf_counter() - t0
        return ok_lat, sheds[0], retry_afters, errors[0], wall, n

    try:
        with ServerThread(srv.app) as st:
            ok_lat, sheds, retry_afters, errors, wall, n = \
                asyncio.run(run(st.base))
    finally:
        if prev_spec is None:
            os.environ.pop("PIO_FAULT_SPEC", None)
        else:
            os.environ["PIO_FAULT_SPEC"] = prev_spec
        faultinject.reset()

    def pct(a, p):
        return float(np.percentile(np.asarray(a), p)) if a else None

    ov = srv.overload_snapshot()
    out = {
        "conc": conc, "max_pending": max_pending,
        "service_ms": service_ms,
        "capacity_qps": round(capacity, 1),
        "offered_qps": round(offered, 1),
        "goodput_qps": round(len(ok_lat) / wall, 1),
        "shed_rate": round(sheds / n, 3),
        "accepted_p50_ms": round(pct(ok_lat, 50), 1) if ok_lat else None,
        "accepted_p99_ms": round(pct(ok_lat, 99), 1) if ok_lat else None,
        "errors": errors,
        "peak_pending": ov["peakPending"],
        "pending_limit": ov["pendingLimit"],
        "retry_after_jittered": len(retry_afters) > 1,
    }
    log(f"[qbench:overload] offered={out['offered_qps']}qps "
        f"(capacity≈{out['capacity_qps']}qps): goodput="
        f"{out['goodput_qps']}qps shed_rate={out['shed_rate']} "
        f"accepted p99={out['accepted_p99_ms']}ms peak_pending="
        f"{out['peak_pending']}/{out['pending_limit']} "
        f"retry_after_jittered={out['retry_after_jittered']} "
        f"errors={errors}")
    return out


def replica_bracket() -> dict:
    """Same-run 1/2/4-replica open-loop QPS bracket (ISSUE 12).

    Real topology: `pio deploy --replicas N` subprocess fleets (front +
    supervisor + coordinator) serving a recommendation model trained
    into a shared sqlite store; every topology is brought up FIRST,
    then the open-loop drive interleaves them round-robin so this
    host's severalfold within-run CPU swing cancels out of the
    within-round ratios (the PR 8 bench protocol). The
    `host_scaleout_ceiling` control — TWO fully independent plain
    engine servers vs ONE under the identical client shape, the best
    case of ANY scale-out — is measured in the same run; a ceiling
    under 1.8x means the bracket reports host capacity, not the fleet.
    """
    import shutil
    import signal
    import subprocess
    import tempfile

    import requests

    brackets = [int(s) for s in os.environ.get(
        "PIO_QBENCH_REPLICAS", "1,2,4").split(",") if s.strip()]
    offered = float(os.environ.get("PIO_QBENCH_REPLICA_QPS", "250"))
    duration = float(os.environ.get("PIO_QBENCH_REPLICA_DURATION", "4"))
    rounds = int(os.environ.get("PIO_QBENCH_REPLICA_ROUNDS", "3"))
    rank = int(os.environ.get("PIO_QBENCH_REPLICA_RANK", "16"))
    n_items = int(os.environ.get("PIO_QBENCH_REPLICA_ITEMS", "4000"))
    n_users = 500
    tmp = tempfile.mkdtemp(prefix="pio_fleetbench_")
    env = {
        **os.environ,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
        "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(tmp, "meta.sqlite"),
        "PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY",
        "JAX_PLATFORMS": "cpu",      # replicas bench the HOST fabric
        "JAX_COMPILATION_CACHE_DIR": os.path.join(tmp, "jaxcache"),
        "PIO_FLEET_SYNC_MS": "500",
    }
    for k in ("PIO_FAULT_SPEC", "PIO_FLEET_WORKER_FAULT_SPEC",
              "PIO_QUERY_REPLICAS", "PIO_QBENCH_QPS"):
        env.pop(k, None)
    engine_dir = os.path.join(tmp, "engine")
    os.makedirs(engine_dir)
    with open(os.path.join(engine_dir, "engine.json"), "w") as f:
        json.dump({
            "id": "default",
            "engineFactory": "incubator_predictionio_tpu.models."
                             "recommendation.RecommendationEngine",
            "datasource": {"params": {"appName": "fleetbench",
                                      "eventNames": ["rate"]}},
            "algorithms": [{"name": "als", "params": {
                "rank": rank, "numIterations": 1, "lambda": 0.01}}],
        }, f)

    from incubator_predictionio_tpu.controller import EngineParams
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.data.storage.datamap import DataMap
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine)
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train

    storage = Storage({k: v for k, v in env.items()
                       if k.startswith("PIO_STORAGE")})
    rng = np.random.default_rng(7)
    app_id = storage.get_meta_data_apps().insert(App(0, "fleetbench", None))
    le = storage.get_l_events()
    le.init(app_id)
    n_events = n_items * 2
    u = rng.integers(0, n_users, n_events)
    i = np.concatenate([np.arange(n_items),
                        rng.integers(0, n_items, n_events - n_items)])
    le.insert_batch([
        Event("rate", "user", str(int(uu)), "item", str(int(ii)),
              properties=DataMap({"rating": float(rr)}))
        for uu, ii, rr in zip(u, i, rng.integers(1, 11, n_events) / 2.0)
    ], app_id)
    params = EngineParams(
        data_source_params={"appName": "fleetbench",
                            "eventNames": ["rate"]},
        algorithm_params_list=[("als", {
            "rank": rank, "numIterations": 1, "lambda": 0.01})],
    )
    ctx = WorkflowContext(app_name="fleetbench", storage=storage)
    run_train(RecommendationEngine()(), params, ctx,
              engine_factory_name="incubator_predictionio_tpu.models."
                                  "recommendation.RecommendationEngine")
    storage.close()
    log(f"[qbench:replicas] trained rank{rank} over {n_items} items "
        f"into {tmp}")

    def _free_port():
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    procs = []

    def store_env(tag):
        """Every topology gets its OWN copy of the trained store: the
        bracket fleets share one engine.json (same factory/variant ⇒
        same fleet group), so on a shared store their coordinators
        would fence-fight over one directive row and aggregate each
        other's replica status rows — three supposedly independent
        topologies coupled through coordination traffic mid-measure."""
        path = os.path.join(tmp, f"meta_{tag}.sqlite")
        shutil.copyfile(os.path.join(tmp, "meta.sqlite"), path)
        return {**env, "PIO_STORAGE_SOURCES_DB_PATH": path}

    def spawn(argv, penv=None):
        p = subprocess.Popen(argv, env=penv or env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        procs.append(p)
        return p

    def wait_http(url, pred, deadline_s=300):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                r = requests.get(url, timeout=2)
                if pred(r):
                    return
            except requests.RequestException:
                pass
            time.sleep(0.5)
        raise RuntimeError(f"{url} not ready")

    def fleet_up(n):
        port = _free_port()
        spawn([sys.executable, "-m",
               "incubator_predictionio_tpu.tools.console", "deploy",
               "--replicas", str(n), "--engine-dir", engine_dir,
               "--ip", "127.0.0.1", "--port", str(port)],
              store_env(f"x{n}"))
        base = f"http://127.0.0.1:{port}"
        wait_http(base + "/healthz",
                  lambda r: r.ok and r.json().get("readyReplicas") == n)
        return base

    def plain_up(tag):
        port = _free_port()
        spawn([sys.executable, "-m",
               "incubator_predictionio_tpu.tools.console", "deploy",
               "--engine-dir", engine_dir, "--ip", "127.0.0.1",
               "--port", str(port)], store_env(tag))
        base = f"http://127.0.0.1:{port}"
        wait_http(base + "/readyz", lambda r: r.ok)
        return base

    out = {"offered_qps": offered, "duration_s": duration,
           "rounds": rounds}
    try:
        bases = {}
        for n in brackets:
            bases[n] = fleet_up(n)
            log(f"[qbench:replicas] fleet x{n} ready at {bases[n]}")
        singles = [plain_up("s0"), plain_up("s1")]
        log(f"[qbench:replicas] ceiling-control servers ready")
        for base in list(bases.values()) + singles:
            load_test(base, 50, 1.0, n_users)    # warm every topology
        per_round: dict = {n: [] for n in brackets}
        ceil_one, ceil_two, ceil_ratio = [], [], []
        for r in range(rounds):
            for n in brackets:
                lat, errs, achieved = load_test(
                    bases[n], offered, duration, n_users, seed=r)
                per_round[n].append(achieved)
                log(f"[qbench:replicas] x{n} (round {r + 1}): "
                    f"goodput={achieved:,.0f}qps errors={errs} "
                    f"p99={np.percentile(lat, 99):.0f}ms" if lat else
                    f"[qbench:replicas] x{n} (round {r + 1}): no "
                    "completions")
            # ceiling control, adjacent in time to the bracket rounds
            one = load_test(singles[0], offered, duration, n_users)[2]
            import threading

            rates = [0.0, 0.0]

            def go(j):
                rates[j] = load_test(singles[j], offered / 2, duration,
                                     n_users)[2]

            ts = [threading.Thread(target=go, args=(j,)) for j in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            two = rates[0] + rates[1]
            ceil_one.append(one)
            ceil_two.append(two)
            ceil_ratio.append(two / one if one else 0.0)
            log(f"[qbench:replicas] ceiling (round {r + 1}): one="
                f"{one:,.0f}qps two-independent={two:,.0f}qps "
                f"ratio={two / one if one else 0:.2f}x")
        for n in brackets:
            out[f"replicas_{n}"] = round(float(np.median(per_round[n])), 1)
            out[f"replicas_{n}_rounds"] = [round(v, 1)
                                           for v in per_round[n]]
        if 1 in brackets:
            for n in brackets:
                if n == 1:
                    continue
                ratios = [per_round[n][r] / per_round[1][r]
                          for r in range(rounds) if per_round[1][r]]
                out[f"speedup_{n}"] = round(float(np.median(ratios)), 2) \
                    if ratios else None
        ceiling = round(float(np.median(ceil_ratio)), 2) \
            if ceil_ratio else None
        out["host_scaleout_ceiling"] = {
            "one_qps": round(float(np.median(ceil_one)), 1),
            "two_independent_qps": round(float(np.median(ceil_two)), 1),
            "ceiling": ceiling,
            "rounds": [round(v, 2) for v in ceil_ratio],
        }
        if ceiling is not None and ceiling < 1.8:
            out["note"] = (
                "host-limited: the ceiling control (TWO fully "
                "independent engine servers vs one, identical client "
                "shape — the best case of ANY scale-out) reached only "
                f"{ceiling}x on this host ({os.cpu_count()} cores; "
                "client+front+replicas saturate them), so the bracket "
                "measures host capacity, not the fleet; a >=1.8x "
                "demonstration needs >=4 usable cores")
            log(f"[qbench:replicas] NOTE: host scale-out ceiling "
                f"{ceiling}x < 1.8x — bracket is host-limited here")
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def catalog_bracket() -> dict:
    """Same-run 10k/100k/1M-item catalog bracket (ISSUE 17).

    For each catalog size: serve a synthetic rank-R ALS model through
    the REAL EngineServer over HTTP and measure serial p50/p99, with
    the flat (unsharded) layout AND the host-sharded layout
    (`PIO_SERVE_SHARD_ITEMS`) — sharded-vs-unsharded at the small sizes
    is the overhead-honesty control. Every sharded answer is compared
    against the unsharded reference on the same query set
    (bit-identity is asserted, not assumed). A zipfian user mix then
    drives the served-result cache on the largest catalog: warm pass
    (all misses = full dispatches) vs hot pass (all hits) gives the
    cache-hit-vs-full-dispatch p50 gap from one run."""
    import threading

    import requests
    from aiohttp import web

    from server_utils import ServerThread

    from incubator_predictionio_tpu.controller import Engine, EngineParams
    from incubator_predictionio_tpu.data.storage.bimap import (
        BiMap, IdentityBiMap)
    from incubator_predictionio_tpu.models.recommendation import (
        ALSAlgorithm, ALSModel, RecommendationDataSource)
    from incubator_predictionio_tpu.ops.als import ALSFactors
    from incubator_predictionio_tpu.workflow.create_server import EngineServer
    from incubator_predictionio_tpu.workflow.plugins import (
        EngineServerPluginContext)

    sizes = [int(s) for s in os.environ.get(
        "PIO_QBENCH_CATALOG_SIZES", "10000,100000,1000000").split(",")
        if s.strip()]
    rank = int(os.environ.get("PIO_QBENCH_CATALOG_RANK", "32"))
    n_users = int(os.environ.get("PIO_QBENCH_CATALOG_USERS", "500"))
    n_q = int(os.environ.get("PIO_QBENCH_CATALOG_N", "120"))
    shard_rows = int(os.environ.get("PIO_QBENCH_SHARD_ROWS", "131072"))

    class Ctx:
        workflow_params = type("WP", (), {"resume": False,
                                          "nan_guard": False})()

        def get_mesh(self):
            return None

        def get_storage(self):
            return None

    def build_dep(n_items, rng):
        item_factors = rng.standard_normal(
            (n_items, rank), dtype=np.float32)
        user_factors = rng.standard_normal(
            (n_users, rank), dtype=np.float32)
        model = ALSModel(
            factors=ALSFactors(user_factors, item_factors,
                               n_users, n_items),
            users=BiMap({str(j): j for j in range(n_users)}),
            items=IdentityBiMap(n_items))
        engine = Engine(data_source_class=RecommendationDataSource,
                        algorithm_class_map={"als": ALSAlgorithm})
        ep = EngineParams.from_json({
            "datasource": {"params": {"appName": "catbench"}},
            "algorithms": [{"name": "als", "params": {
                "rank": rank, "shardedServing": "never"}}],
        })
        return engine.prepare_deployment(Ctx(), ep, [model])

    def skeleton_server(dep, **overload_kw):
        srv = EngineServer.__new__(EngineServer)  # no storage-backed load
        srv.deployment = dep
        srv.instance = None
        srv.plugins = EngineServerPluginContext()
        srv._lock = threading.Lock()
        srv._query_count = 0
        srv.feedback = False
        srv._batch_queue = None
        srv._init_overload_state(query_deadline_ms=0, **overload_kw)
        srv.app = web.Application()
        srv.app.add_routes([web.post("/queries.json", srv.handle_query)])
        return srv

    def serve_and_measure(dep, users, check_users=(), cache_size=0,
                          ttl_ms=60_000, passes=1):
        """Serial closed-loop latencies per pass + the check-set
        responses + the server's cache snapshot (if armed)."""
        srv = skeleton_server(
            dep, query_cache_size=cache_size,
            query_cache_ttl_ms=ttl_ms if cache_size else None)
        per_pass, checks = [], {}
        with ServerThread(srv.app) as st:
            sess = requests.Session()
            for u in ("0", "1"):     # compile + pool warm-up
                r = sess.post(st.base + "/queries.json",
                              json={"user": u, "num": 10}, timeout=600)
                assert r.status_code == 200, r.text
            for _p in range(passes):
                lat = []
                for u in users:
                    t0 = time.perf_counter()
                    r = sess.post(st.base + "/queries.json",
                                  json={"user": u, "num": 10}, timeout=600)
                    lat.append((time.perf_counter() - t0) * 1000)
                    assert r.status_code == 200, r.text
                per_pass.append(lat)
            for u in check_users:
                checks[u] = sess.post(
                    st.base + "/queries.json",
                    json={"user": u, "num": 10}, timeout=600).json()
        snap = (srv._query_cache.snapshot()
                if srv._query_cache is not None else None)
        srv._query_executor.shutdown(wait=False)
        return per_pass, checks, snap

    def pct(a, p):
        return round(float(np.percentile(np.asarray(a), p)), 2)

    rng = np.random.default_rng(42)
    users = [str(int(v)) for v in rng.integers(0, n_users, n_q)]
    check_users = [str(j) for j in range(0, n_users, n_users // 16)]

    prev_knob = os.environ.get("PIO_SERVE_SHARD_ITEMS")
    out: dict = {"rank": rank, "queries_per_point": n_q,
                 "shard_rows": shard_rows, "sizes": {}}
    try:
        for n_items in sizes:
            srng = np.random.default_rng(n_items)
            os.environ.pop("PIO_SERVE_SHARD_ITEMS", None)
            flat_dep = build_dep(n_items, srng)
            (flat_lat,), flat_checks, _ = serve_and_measure(
                flat_dep, users, check_users)
            row = {"flat_p50_ms": pct(flat_lat, 50),
                   "flat_p99_ms": pct(flat_lat, 99)}
            # ≥8 shards at EVERY size: the small catalogs are the
            # overhead-honesty control (what does scanning cost when
            # nothing needed sharding?), capped by the env knob
            rows = min(shard_rows, max(1, n_items // 8))
            if rows < n_items:
                # fresh deployment, SAME factors (seeded rng): the
                # catalog facade picks the host-sharded layout now
                os.environ["PIO_SERVE_SHARD_ITEMS"] = str(rows)
                shard_dep = build_dep(n_items, np.random.default_rng(
                    n_items))
                (shard_lat,), shard_checks, _ = serve_and_measure(
                    shard_dep, users, check_users)
                cat = shard_dep.models[0].catalog()
                assert cat.layout == "host", cat.layout
                row.update({
                    "sharded_p50_ms": pct(shard_lat, 50),
                    "sharded_p99_ms": pct(shard_lat, 99),
                    "shards": cat.n_shards,
                    # the acceptance bar: sharded answers ARE the
                    # unsharded answers, through the full HTTP path
                    "identical_to_flat": shard_checks == flat_checks,
                })
                assert row["identical_to_flat"], (
                    f"sharded != flat at {n_items} items")
                del shard_dep
            out["sizes"][str(n_items)] = row
            log(f"[qbench:catalog] {n_items:,} items: "
                + " ".join(f"{k}={v}" for k, v in row.items()))
            del flat_dep

        # -- cache on/off at a zipfian user mix, largest catalog ------
        os.environ["PIO_SERVE_SHARD_ITEMS"] = str(shard_rows)
        big = max(sizes)
        dep = build_dep(big, np.random.default_rng(big))
        zipf = [str(int(v) % n_users)
                for v in np.random.default_rng(5).zipf(1.3, n_q)]
        # cache OFF = every query a full sharded dispatch (the honest
        # dispatch p50 — the cache-armed warm pass already hits on the
        # zipf head's within-pass repeats)
        (cold,), _c, _s = serve_and_measure(dep, zipf)
        # cache ON: pass 1 warms, pass 2 repeats the identical mix
        # (all hits)
        (warm, hot), _c, snap = serve_and_measure(
            dep, zipf, cache_size=4096, passes=2)
        out["cache"] = {
            "catalog_items": big,
            "zipf_users": len(set(zipf)),
            "dispatch_p50_ms": pct(cold, 50),
            "mixed_p50_ms": pct(warm, 50),
            "hit_p50_ms": pct(hot, 50),
            "hit_speedup": round(pct(cold, 50) / max(pct(hot, 50), 1e-9),
                                 1),
            "hits": snap["hits"], "misses": snap["misses"],
        }
        assert snap["hits"] >= n_q, snap       # pass 2 must be all hits
        assert out["cache"]["hit_p50_ms"] < out["cache"]["dispatch_p50_ms"]
        log(f"[qbench:catalog] cache @ {big:,} items: "
            f"dispatch p50={out['cache']['dispatch_p50_ms']}ms vs "
            f"hit p50={out['cache']['hit_p50_ms']}ms "
            f"({out['cache']['hit_speedup']}x)")
        del dep
    finally:
        if prev_knob is None:
            os.environ.pop("PIO_SERVE_SHARD_ITEMS", None)
        else:
            os.environ["PIO_SERVE_SHARD_ITEMS"] = prev_knob
    out["note"] = (
        f"{os.cpu_count()}-core host, serial closed-loop over HTTP; "
        "absolute latencies are host-CPU-bound (the 2-core ceiling of "
        "the PR 8/12 benches applies) — the signal is the WITHIN-RUN "
        "shape: sharded-vs-flat overhead at small catalogs, bounded "
        "growth to 1M items, and the cache-hit-vs-dispatch gap")
    out["overhead_fix"] = (
        "profiled the CPU-local stack (ISSUE 17 satellite): ~0.6 ms of "
        "the per-query cost was eager jnp dispatch in ops/topk.py — a "
        "fresh jnp.zeros exclude mask built per query plus jnp.asarray "
        "wrappers bypassing jit's C++ argument path; caching the "
        "no-exclude mask per catalog size and passing raw arrays cut "
        "in-process predict p50 0.714→0.383 ms and full-HTTP p50 "
        "2.36→2.06 ms on the 26744-item rank-32 reference (same "
        "executable, bit-identical answers)")
    return out


def multitenant_bracket() -> dict:
    """Same-run 1/8/32-app multi-tenant bracket (ISSUE 19).

    ONE storage-backed EngineServer with the tenant mux armed at
    PIO_QBENCH_TENANT_RESIDENT (default 6 — below the 32-app point so
    the largest bracket size observes real eviction churn, the
    acceptance topology). Every app is a trained instance in the
    Models DAO; each bracket size drives an opening sweep (first touch
    = lazy cold load through verified-read + validation gate) then a
    zipfian per-tenant mix, and EVERY query is classified hit-vs-cold
    by the mux's coldLoads counter — no positional assumptions — so
    resident-hit vs cold-load p50/p99 come from one process in one
    run. The classic no-header default-app path is measured alongside
    as the mux-overhead control: same engine, same 2-core host, same
    run, mux routing off."""
    import requests

    import lifecycle_engine
    from server_utils import ServerThread

    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.create_server import EngineServer

    sizes = [int(s) for s in os.environ.get(
        "PIO_QBENCH_TENANT_SIZES", "1,8,32").split(",") if s.strip()]
    resident = int(os.environ.get("PIO_QBENCH_TENANT_RESIDENT", "6"))
    n_q = int(os.environ.get("PIO_QBENCH_TENANT_N", "160"))

    storage = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY",
    })
    all_apps = [f"bt{j:02d}" for j in range(max(sizes))]
    for name in all_apps:
        storage.get_meta_data_apps().insert(App(id=0, name=name))
        run_train(lifecycle_engine.engine_factory(),
                  lifecycle_engine.engine_params(name),
                  WorkflowContext(app_name=name, storage=storage),
                  engine_factory_name="lifecycle")
        time.sleep(0.002)  # strictly ordered start_times
    # the default app trains LAST so the classic no-header path serves
    # the newest COMPLETED instance (the single-tenant bootstrap load)
    run_train(lifecycle_engine.engine_factory(),
              lifecycle_engine.engine_params("default-app"),
              WorkflowContext(app_name="default-app", storage=storage),
              engine_factory_name="lifecycle")

    srv = EngineServer(lifecycle_engine.engine_factory(),
                       engine_factory_name="lifecycle",
                       storage=storage,
                       tenant_max_resident=resident)
    mux = srv._tenants
    assert mux is not None

    def pct(a, p):
        return round(float(np.percentile(np.asarray(a), p)), 2)

    out: dict = {"max_resident": resident, "queries_per_point": n_q,
                 "sizes": {}}
    with ServerThread(srv.app) as st:
        sess = requests.Session()

        def q(app=None, user="u0"):
            """One closed-loop query; (latency ms, was-cold-load)."""
            headers = {"X-Pio-App": app} if app else {}
            before = mux.snapshot()["coldLoads"]
            t0 = time.perf_counter()
            r = sess.post(st.base + "/queries.json",
                          json={"user": user}, headers=headers,
                          timeout=600)
            dt = (time.perf_counter() - t0) * 1000
            assert r.status_code == 200, (app, r.status_code, r.text)
            return dt, mux.snapshot()["coldLoads"] > before

        for u in ("u0", "u1"):  # connection-pool warm-up, classic path
            q(user=u)

        for n in sizes:
            apps = all_apps[:n]
            snap0 = mux.snapshot()
            hit, cold = [], []
            # opening sweep: first touch per app (cold unless a
            # previous bracket size left it resident)
            for a in apps:
                dt, was_cold = q(a)
                (cold if was_cold else hit).append(dt)
            rng = np.random.default_rng(n)
            for v in rng.zipf(1.3, n_q):
                dt, was_cold = q(apps[(int(v) - 1) % n])
                (cold if was_cold else hit).append(dt)
            snap1 = mux.snapshot()
            row = {
                "apps": n,
                "queries": n + n_q,
                "hit_p50_ms": pct(hit, 50) if hit else None,
                "hit_p99_ms": pct(hit, 99) if hit else None,
                "cold_p50_ms": pct(cold, 50) if cold else None,
                "cold_p99_ms": pct(cold, 99) if cold else None,
                "cold_loads": snap1["coldLoads"] - snap0["coldLoads"],
                "evictions": snap1["evictions"] - snap0["evictions"],
                "resident": snap1["resident"],
            }
            out["sizes"][str(n)] = row
            log(f"[qbench:tenants] {n} apps: "
                + " ".join(f"{k}={v}" for k, v in row.items()
                           if k != "apps"))

        # mux-overhead control: the classic single-tenant path
        classic = [q()[0] for _ in range(40)]
        out["classic_p50_ms"] = pct(classic, 50)

    srv._query_executor.shutdown(wait=False)
    from incubator_predictionio_tpu.common import telemetry
    telemetry.registry().unregister_collector("engineserver")

    big = max(sizes)
    big_row = out["sizes"][str(big)]
    if big > resident:
        # the acceptance bar: more apps than residency ⇒ churn is
        # OBSERVED (evictions fired), and a resident hit beats the
        # cold lazy-load path it avoids
        assert big_row["evictions"] >= 1, big_row
        assert big_row["hit_p50_ms"] < big_row["cold_p50_ms"], big_row
    out["note"] = (
        f"{os.cpu_count()}-core host, serial closed-loop over HTTP; "
        "absolute latencies are host-CPU-bound (the same 2-core "
        "ceiling as the catalog/replica brackets) — the signal is the "
        "WITHIN-RUN shape: resident-hit vs cold-load gap, hit p50 "
        "flat across 1/8/32 apps, eviction churn only past the "
        "residency bound, and classic-vs-mux routing overhead")
    return out


def main() -> int:
    n_items = int(os.environ.get("PIO_QBENCH_ITEMS", "26744"))
    rank = int(os.environ.get("PIO_QBENCH_RANK", "32"))
    n_users = int(os.environ.get("PIO_QBENCH_USERS", "3000"))
    n_q = int(os.environ.get("PIO_QBENCH_N", "200"))
    from bench_common import ensure_platform_or_exit

    ensure_platform_or_exit()
    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    import requests
    from server_utils import ServerThread

    from incubator_predictionio_tpu.controller import EngineParams
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.data.storage.datamap import DataMap
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.models.recommendation import RecommendationEngine
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.create_server import EngineServer

    storage = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
    })

    # Catalog-scale synthetic ratings: every item rated ≥ once so the item
    # factor matrix spans the full ML-20M catalog.
    rng = np.random.default_rng(0)
    t0 = time.time()
    app_id = storage.get_meta_data_apps().insert(App(0, "qbench", None))
    le = storage.get_l_events()
    le.init(app_id)
    n_events = max(n_items * 2, 50_000)
    u = rng.integers(0, n_users, n_events)
    i = np.concatenate([np.arange(n_items), rng.integers(0, n_items, n_events - n_items)])
    r = rng.integers(1, 11, n_events) / 2.0
    events = [
        Event("rate", "user", str(int(uu)), "item", str(int(ii)),
              properties=DataMap({"rating": float(rr)}))
        for uu, ii, rr in zip(u, i, r)
    ]
    le.insert_batch(events, app_id)
    log(f"[qbench] seeded {n_events} events over {n_items} items in "
        f"{time.time()-t0:.1f}s")

    engine = RecommendationEngine()()
    ctx = WorkflowContext(app_name="qbench", storage=storage)
    params = EngineParams(
        data_source_params={"appName": "qbench", "eventNames": ["rate"]},
        algorithm_params_list=[("als", {
            "rank": rank, "numIterations": 1, "lambda": 0.01,
        })],
    )
    t0 = time.time()
    run_train(engine, params, ctx, engine_factory_name="qbench")
    log(f"[qbench] train+persist {time.time()-t0:.1f}s "
        f"(backend={jax.default_backend()})")

    # Device-dispatch round-trip floor (tunnel/attachment artifact).
    import jax.numpy as jnp

    one = jax.jit(lambda x: x + 1.0)
    _ = jax.device_get(one(jnp.float32(1)))
    t0 = time.time()
    for _k in range(20):
        _ = jax.device_get(one(jnp.float32(1)))
    rtt_ms = (time.time() - t0) / 20 * 1000
    log(f"[qbench] device dispatch RTT {rtt_ms:.2f}ms")

    server = EngineServer(engine, engine_factory_name="qbench", storage=storage)

    # -- on-chip predict time, tunnel-free (VERDICT r3 weak #5) -----------
    # One dispatch runs the EXACT hot-path computation (matvec + mask +
    # top_k over the real deployed item factors) R times with a chained
    # data dependency; the slope (T(R2)-T(R1))/(R2-R1) cancels dispatch
    # RTT, host decode, and tunnel artifacts, leaving pure device
    # execution time per predict. A jax.profiler device trace of the
    # same dispatches is captured for the record (PIO_QBENCH_TRACE_DIR).
    import functools

    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("reps", "k"))
    def _looped_predict(user_vec, items, mask, reps: int, k: int):
        def body(uv, _):
            scores = items @ uv
            scores = jnp.where(mask, -jnp.inf, scores)
            s, idx = jax.lax.top_k(scores, k)
            # fold the result into the carry: iterations chain, so XLA
            # can neither elide nor overlap them
            return uv + s[0] * jnp.float32(1e-20), (s[0], idx[0])
        return jax.lax.scan(body, user_vec, None, length=reps)

    model0 = server.deployment.models[0]
    real_items = jnp.asarray(
        np.asarray(model0.factors.item_factors, np.float32))
    mask = jnp.zeros((real_items.shape[0],), bool)
    uv0 = jnp.asarray(rng.standard_normal(rank).astype(np.float32))
    def _run_to_completion(reps):
        carry, _ys = _looped_predict(uv0, real_items, mask, reps, 10)
        # completion barrier MUST be a device_get: through the remote-
        # PJRT tunnel block_until_ready can return before the device
        # finishes (same protocol as train_als's timed path)
        _ = jax.device_get(carry[:1])

    # the per-query on-chip cost is O(10 us) — far below tunnel RTT
    # noise — so the rep spread must be wide enough that the extra
    # device work clears the +-few-ms dispatch jitter
    r_lo, r_hi = 64, 4096
    slope_times = {}
    for reps in (r_lo, r_hi):
        _run_to_completion(reps)
        t0 = time.perf_counter()
        for _r in range(5):
            _run_to_completion(reps)
        slope_times[reps] = (time.perf_counter() - t0) / 5
    onchip_ms = (slope_times[r_hi] - slope_times[r_lo]) / (r_hi - r_lo) * 1000
    log(f"[qbench] ON-CHIP predict (matvec+top_k @ {real_items.shape}) = "
        f"{onchip_ms:.3f}ms/query (dispatch-amortized scan slope; "
        f"single-dispatch walls: {r_lo}reps {slope_times[r_lo]*1000:.1f}ms, "
        f"{r_hi}reps {slope_times[r_hi]*1000:.1f}ms)")
    trace_dir = os.environ.get("PIO_QBENCH_TRACE_DIR")
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            jax.block_until_ready(
                _looped_predict(uv0, real_items, mask, 8, 10))
        log(f"[qbench] device trace written to {trace_dir}")
    print(json.dumps({
        "metric": f"on-chip predict time ({jax.default_backend()}, "
                  f"{real_items.shape[0]} items, rank {rank})",
        "value": round(onchip_ms, 4), "unit": "ms/query",
    }), flush=True)

    # In-process predict latency (algorithm hot path, no HTTP).
    dep = server.deployment
    lat_predict = []
    for _k in range(n_q):
        q = {"user": str(int(rng.integers(0, n_users))), "num": 10}
        t0 = time.perf_counter()
        out = dep.query(q)
        lat_predict.append((time.perf_counter() - t0) * 1000)
    assert out["itemScores"], "query returned nothing"

    # Full HTTP path.
    lat_http = []
    with ServerThread(server.app) as st:
        sess = requests.Session()
        sess.post(st.base + "/queries.json", json={"user": "0", "num": 10})
        for _k in range(n_q):
            body = {"user": str(int(rng.integers(0, n_users))), "num": 10}
            t0 = time.perf_counter()
            resp = sess.post(st.base + "/queries.json", json=body)
            lat_http.append((time.perf_counter() - t0) * 1000)
        assert resp.status_code == 200, resp.text

    def pct(a, p):
        return float(np.percentile(np.asarray(a), p))

    log(f"[qbench] predict p50={pct(lat_predict, 50):.2f}ms "
        f"p95={pct(lat_predict, 95):.2f}ms p99={pct(lat_predict, 99):.2f}ms")
    log(f"[qbench] http    p50={pct(lat_http, 50):.2f}ms "
        f"p95={pct(lat_http, 95):.2f}ms p99={pct(lat_http, 99):.2f}ms")
    log(f"[qbench] stack-only http overhead ≈ "
        f"{pct(lat_http, 50) - pct(lat_predict, 50):.2f}ms; device dispatch "
        f"RTT {rtt_ms:.2f}ms of predict is attachment latency")

    # -- open-loop load test at fixed offered rates -----------------------
    load_detail = {}
    qps_env = os.environ.get("PIO_QBENCH_QPS")
    if qps_env:
        rates = [float(s) for s in qps_env.split(",")]
        duration = float(os.environ.get("PIO_QBENCH_DURATION", "5"))
        batch_ms = float(os.environ.get("PIO_QBENCH_BATCH_MS", "5"))
        for label, window in (("unbatched", 0.0), ("batched", batch_ms)):
            srv = EngineServer(
                engine, engine_factory_name="qbench", storage=storage,
                batch_window_ms=window,
            )
            with ServerThread(srv.app) as st:
                for rate in rates:
                    lat, errs, achieved = load_test(
                        st.base, rate, duration, n_users)
                    key = f"{label}_{int(rate)}qps"
                    load_detail[key] = {
                        "p50_ms": round(pct(lat, 50), 2) if lat else None,
                        "p95_ms": round(pct(lat, 95), 2) if lat else None,
                        "p99_ms": round(pct(lat, 99), 2) if lat else None,
                        "achieved_qps": round(achieved, 1),
                        "errors": errs,
                    }
                    log(f"[qbench:load] {label} window={window}ms "
                        f"offered={rate:.0f}qps achieved={achieved:.0f}qps "
                        f"p50={load_detail[key]['p50_ms']}ms "
                        f"p99={load_detail[key]['p99_ms']}ms errors={errs}")

    # -- overload bracket: offered load ≫ capacity (ISSUE 6) --------------
    overload_detail = None
    if os.environ.get("PIO_QBENCH_OVERLOAD", "1") != "0":
        overload_detail = overload_bracket(engine, storage, n_users)

    # -- 10k/100k/1M catalog bracket + cache gap (ISSUE 17) ---------------
    catalog_detail = None
    if os.environ.get("PIO_QBENCH_CATALOG", "1") != "0":
        try:
            catalog_detail = catalog_bracket()
        except Exception as e:  # noqa: BLE001 - bracket is additive
            log(f"[qbench:catalog] bracket failed: {e}")

    # -- replica-fleet QPS bracket + ceiling control (ISSUE 12) -----------
    replica_detail = None
    if os.environ.get("PIO_QBENCH_REPLICAS", "1,2,4") != "0":
        try:
            replica_detail = replica_bracket()
        except Exception as e:  # noqa: BLE001 - bracket is additive
            log(f"[qbench:replicas] bracket failed: {e}")

    # -- 1/8/32-app multi-tenant mux bracket (ISSUE 19) -------------------
    tenant_detail = None
    if os.environ.get("PIO_QBENCH_TENANTS", "1") != "0":
        try:
            tenant_detail = multitenant_bracket()
        except Exception as e:  # noqa: BLE001 - bracket is additive
            log(f"[qbench:tenants] bracket failed: {e}")

    p50 = pct(lat_http, 50)
    print(json.dumps({
        "metric": f"pio query p50 /queries.json {n_items}-item catalog "
                  f"rank{rank} ({jax.default_backend()})",
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(10.0 / p50, 2),
        "detail": {
            "predict_p50_ms": round(pct(lat_predict, 50), 2),
            "http_p50_ms": round(p50, 2),
            "http_p99_ms": round(pct(lat_http, 99), 2),
            "dispatch_rtt_ms": round(rtt_ms, 2),
            **({"load": load_detail} if load_detail else {}),
            **({"overload": overload_detail} if overload_detail else {}),
            **({"catalog": catalog_detail} if catalog_detail else {}),
            **({"replicas": replica_detail} if replica_detail else {}),
            **({"multitenant": tenant_detail} if tenant_detail else {}),
        },
    }))
    here = os.path.dirname(os.path.abspath(__file__))
    if catalog_detail is not None:
        try:
            with open(os.path.join(here, "BASELINE.json")) as f:
                doc = json.load(f)
            doc.setdefault("published", {})[
                "measured_query_catalog"] = catalog_detail
            with open(os.path.join(here, "BASELINE.json"), "w") as f:
                json.dump(doc, f, indent=2)
        except Exception as e:  # noqa: BLE001
            log(f"[qbench:catalog] could not persist to BASELINE: {e}")
    if replica_detail is not None:
        try:
            with open(os.path.join(here, "BASELINE.json")) as f:
                doc = json.load(f)
            doc.setdefault("published", {})[
                "measured_query_replicas"] = replica_detail
            with open(os.path.join(here, "BASELINE.json"), "w") as f:
                json.dump(doc, f, indent=2)
        except Exception as e:  # noqa: BLE001
            log(f"[qbench:replicas] could not persist to BASELINE: {e}")
    if tenant_detail is not None:
        try:
            with open(os.path.join(here, "BASELINE.json")) as f:
                doc = json.load(f)
            doc.setdefault("published", {})[
                "measured_multitenant"] = tenant_detail
            with open(os.path.join(here, "BASELINE.json"), "w") as f:
                json.dump(doc, f, indent=2)
        except Exception as e:  # noqa: BLE001
            log(f"[qbench:tenants] could not persist to BASELINE: {e}")
    if replica_detail is not None:
        try:
            with open(os.path.join(here, "MULTICHIP_fleet.json"),
                      "w") as f:
                json.dump({
                    "mode": "query_replica_bracket",
                    "backend": jax.default_backend(),
                    "cores": os.cpu_count(),
                    **replica_detail,
                }, f, indent=2)
        except Exception as e:  # noqa: BLE001
            log(f"[qbench:replicas] could not persist MULTICHIP: {e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
