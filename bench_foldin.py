"""Benchmark: online fold-in freshness lag vs. event rate (ISSUE 13).

Runs the REAL streaming-online-learning path end to end: a jax-free
counting engine is trained and deployed behind the actual EngineServer
with the fold-in loop armed (PIO_FOLDIN_MS), a producer appends rating
events into the JSONL event log at a target rate, and every ~1 s it
drops a MARKER user's first-ever event and measures the wall time
until a live `/queries.json` answer reflects it (known=true) — the
event→served freshness lag, which is what "online learning" buys.

Same-run bracket discipline (the PR 8 precedent: this 2-core sandbox's
CPU swings severalfold within a run, so absolutes are only comparable
inside one process): every rate runs in the same process against its
own fresh store, `host_loop_mops` rides along as the cross-host
denominator, and the fold-in interval is printed next to the lags
(the lag floor is ~interval/2 + publish cost by construction).

Persists to BASELINE.json `published.measured_foldin_freshness`.

Env: PIO_FBENCH_RATES ("20,100" events/sec), PIO_FBENCH_DURATION (6 s
per rate), PIO_FBENCH_FOLDIN_MS (200).

Also the engine + server module for its own subprocess
(`python bench_foldin.py --server PORT`): both sides run as __main__,
so pickled models round-trip.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def host_calibration() -> float:
    t0 = time.perf_counter()
    s = 0
    for i in range(2_000_000):
        s += i
    return 2.0 / (time.perf_counter() - t0)


# -- the jax-free engine (importable from the subprocess as __main__) -----

@dataclasses.dataclass
class FoldinBenchModel:
    scores: dict

    def example_query(self):
        return {"user": "golden"}


def _mk_engine():
    from incubator_predictionio_tpu.controller.algorithm import Algorithm
    from incubator_predictionio_tpu.controller.datasource import DataSource
    from incubator_predictionio_tpu.controller.engine import Engine

    class BenchDataSource(DataSource):
        def read_training(self, ctx):
            s = ctx.get_storage()
            app = (s.get_meta_data_apps().get_by_name(ctx.app_name)
                   if ctx.app_name else None)
            return list(s.get_l_events().find(app.id)) if app else []

    class BenchAlgorithm(Algorithm):
        def train(self, ctx, events):
            scores = {}
            for e in events:
                if e.event == "rate" and e.entity_id:
                    scores[e.entity_id] = scores.get(e.entity_id, 0.0) \
                        + float(e.properties.get_or_else("rating", 1.0))
            return FoldinBenchModel(scores)

        def predict(self, model, query):
            u = str(query["user"])
            if u == "golden" or u in model.scores:
                return {"user": u, "known": True,
                        "score": float(model.scores.get(u, 0.0))}
            return {"user": u, "known": False}

        def fold_in(self, model, events, ctx, data_source_params=None):
            scores = dict(model.scores)
            changed = False
            for e in events:
                if e.get("event") == "rate" and e.get("entityId"):
                    props = e.get("properties") or {}
                    scores[str(e["entityId"])] = \
                        scores.get(str(e["entityId"]), 0.0) \
                        + float(props.get("rating", 1.0))
                    changed = True
            return FoldinBenchModel(scores) if changed else None

        def prepare_model_for_persistence(self, model):
            return model

        def restore_model(self, stored, ctx):
            return stored

    return Engine(BenchDataSource, None, {"": BenchAlgorithm}, None)


def _serve(port: int) -> int:
    import logging

    logging.basicConfig(level=logging.WARNING)
    logging.getLogger("aiohttp.access").setLevel(logging.ERROR)
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.workflow.create_server import (
        EngineServer, run_engine_server)

    server = EngineServer(_mk_engine(), engine_factory_name="foldbench",
                          storage=Storage.instance())
    run_engine_server(server, "127.0.0.1", port)
    return 0


# -- the driver ------------------------------------------------------------

def _storage_env(tmp: str, foldin_ms: int) -> dict:
    return {
        **os.environ,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "JL",
        "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
        "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(tmp, "meta.sqlite"),
        "PIO_STORAGE_SOURCES_JL_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_JL_PATH": os.path.join(tmp, "events"),
        "PIO_COMPILATION_CACHE": "0",
        "JAX_PLATFORMS": "cpu",
        "PIO_FOLDIN_MS": str(foldin_ms),
        "PIO_METRICS": os.environ.get("PIO_METRICS", "1"),
    }


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pct(a, p):
    a = sorted(a)
    return a[min(len(a) - 1, round(p / 100 * (len(a) - 1)))]


def _run_rate(rate: float, duration: float, foldin_ms: int) -> dict:
    import requests

    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.data.storage.datamap import DataMap
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.controller.engine import EngineParams

    tmp = tempfile.mkdtemp(prefix=f"foldbench_{int(rate)}_")
    env = _storage_env(tmp, foldin_ms)
    storage = Storage({k: v for k, v in env.items()
                       if k.startswith("PIO_STORAGE")})
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="fb"))
    le = storage.get_l_events()
    le.insert(Event(event="rate", entity_type="user", entity_id="seed",
                    properties=DataMap({"rating": 1.0})), app_id)
    ctx = WorkflowContext(app_name="fb", storage=storage)
    run_train(_mk_engine(),
              EngineParams(data_source_params={"appName": "fb"},
                           algorithm_params_list=[("", {})]),
              ctx, engine_factory_name="foldbench")

    port = _free_port()
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__),
                             "--server", str(port)],
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                requests.get(base + "/status", timeout=2)
                break
            except requests.RequestException:
                time.sleep(0.1)
        else:
            raise RuntimeError("bench server not ready")

        interval = 1.0 / rate
        t_end = time.monotonic() + duration
        next_t = time.monotonic()
        next_marker = time.monotonic() + 0.5
        sent = 0
        marker_i = 0
        lags_ms: list[float] = []
        pending = None      # (user, t_inserted)
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now >= next_t:
                le.insert(Event(event="rate", entity_type="user",
                                entity_id=f"filler{sent % 500}",
                                properties=DataMap({"rating": 1.0})),
                          app_id)
                sent += 1
                next_t += interval
            if pending is None and now >= next_marker:
                user = f"marker{marker_i}"
                marker_i += 1
                le.insert(Event(event="rate", entity_type="user",
                                entity_id=user,
                                properties=DataMap({"rating": 9.0})),
                          app_id)
                sent += 1
                pending = (user, time.monotonic())
            if pending is not None:
                user, t0 = pending
                try:
                    doc = requests.post(
                        base + "/queries.json", json={"user": user},
                        timeout=5).json()
                except requests.RequestException:
                    doc = {}
                if doc.get("known"):
                    lags_ms.append((time.monotonic() - t0) * 1e3)
                    pending = None
                    next_marker = time.monotonic() + 0.5
                elif time.monotonic() - t0 > 30:
                    pending = None      # stuck marker: drop, move on
                    next_marker = time.monotonic()
            time.sleep(0.005)
        doc = requests.get(base + "/status", timeout=5).json()
        fold = doc.get("foldin") or {}
        out = {
            "offered_eps": rate,
            "achieved_eps": round(sent / duration, 1),
            "samples": len(lags_ms),
            "freshness_p50_ms": round(_pct(lags_ms, 50), 1)
            if lags_ms else None,
            "freshness_p90_ms": round(_pct(lags_ms, 90), 1)
            if lags_ms else None,
            "publishes": fold.get("publishes"),
            "events_folded": fold.get("events"),
        }
        proc.send_signal(__import__("signal").SIGTERM)
        proc.wait(timeout=30)
        return out
    finally:
        storage.close()
        if proc.poll() is None:
            proc.kill()
        proc.communicate()


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--server":
        return _serve(int(sys.argv[2]))
    rates = [float(r) for r in
             os.environ.get("PIO_FBENCH_RATES", "20,100").split(",")]
    duration = float(os.environ.get("PIO_FBENCH_DURATION", "6"))
    foldin_ms = int(os.environ.get("PIO_FBENCH_FOLDIN_MS", "200"))
    mops = host_calibration()
    log(f"[foldbench] host {mops:.1f} Mops, fold-in every {foldin_ms} "
        f"ms, {duration:.0f}s per rate")
    results = {"foldin_ms": foldin_ms, "host_loop_mops": round(mops, 1),
               "rates": {}, "note": (
                   "freshness lag = marker event append -> first served "
                   "query reflecting it; floor ~ foldin_ms/2 + publish "
                   "cost (full artifact serialize+validate per "
                   "increment). Same-run bracket; absolutes are not "
                   "comparable across runs on this host.")}
    for rate in rates:
        res = _run_rate(rate, duration, foldin_ms)
        results["rates"][str(int(rate))] = res
        log(f"[foldbench] rate {rate:.0f} ev/s: achieved "
            f"{res['achieved_eps']} ev/s, freshness p50 "
            f"{res['freshness_p50_ms']} ms, p90 "
            f"{res['freshness_p90_ms']} ms over {res['samples']} "
            f"marker(s), {res['publishes']} publish(es)")
        print(json.dumps({
            "metric": f"foldin freshness p50 at {rate:.0f} ev/s",
            "value": res["freshness_p50_ms"], "unit": "ms",
        }), flush=True)
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE.json")
    try:
        with open(base_path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})[
            "measured_foldin_freshness"] = results
        with open(base_path, "w") as f:
            json.dump(doc, f, indent=2)
        log("[foldbench] persisted BASELINE.json "
            "published.measured_foldin_freshness")
    except Exception as e:  # noqa: BLE001
        log(f"[foldbench] could not persist: {e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
