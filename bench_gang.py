"""Gang-restart recovery bench: how fast does supervised multi-worker
training detect a dead/hung worker and resume from checkpoint?

Runs a REAL 2-process sharded-ALS gang (tests/gang_als_worker.py) under
parallel/supervisor.Supervisor and measures, with wall-clock brackets:

- kill bracket: SIGKILL one worker mid-training →
  ``detect_kill_ms`` (death → supervisor failure event),
  ``relaunch_ms`` (failure → relaunched gang, incl. jittered backoff),
  ``recover_to_done_ms`` (relaunch → training complete).
- stall bracket (``PIO_GANG_BENCH_STALL=0`` skips): SIGSTOP one worker →
  ``detect_stall_ms`` (stop → failure event; dominated by the
  configured ``PIO_WORKER_STALL_MS``, reported alongside it so the
  detector overhead is visible).

Like every bench here: same-run brackets only — this host's CPU varies
wildly run to run (BASELINE.md), so the numbers are for shape, not
absolutes. Results print as one JSON line and persist under
``BASELINE.json.published.measured_gang_recovery`` plus
``MULTICHIP_gang.json`` (the multichip bracket the roadmap asks for).
"""

import json
import os
import signal
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from incubator_predictionio_tpu.parallel.supervisor import (  # noqa: E402
    COMPLETED,
    GangConfig,
    Supervisor,
)

WORKER = os.path.join(HERE, "tests", "gang_als_worker.py")
N_ITERS = 8
STALL_MS = 6000.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _gang(tmp, tag, per_worker_env=None, max_restarts=3):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_COMPILATION_CACHE_DIR": os.path.join(tmp, "xla_cache"),
    }
    env.pop("PIO_FAULT_SPEC", None)
    return Supervisor(
        [sys.executable, WORKER, os.path.join(tmp, f"{tag}.npz"),
         os.path.join(tmp, f"ckpt_{tag}"), str(N_ITERS)],
        num_workers=2, env=env, per_worker_env=per_worker_env,
        config=GangConfig(num_workers=2, heartbeat_ms=250.0,
                          stall_ms=STALL_MS, init_grace_ms=300_000.0,
                          max_restarts=max_restarts, poll_ms=50.0),
        run_dir=os.path.join(tmp, f"run_{tag}"),
    )


def _run_async(sup):
    box = {}
    t = threading.Thread(
        target=lambda: box.update(outcome=sup.run()), daemon=True)
    t.start()
    return t, box


def _wait_first_beat(sup, box, worker=1, attempt=0, timeout=600):
    """Block until `worker` of `attempt` starts beating (mid-training),
    then return its pid."""
    deadline = time.monotonic() + timeout
    hb = os.path.join(sup.run_dir, f"worker_{worker}.hb")
    while time.monotonic() < deadline and not box:
        start = next((e for e in list(sup.events)
                      if e["type"] == "gangStart"
                      and e["attempt"] == attempt), None)
        if start and os.path.exists(hb):
            return start["pids"][worker]
        time.sleep(0.02)
    raise RuntimeError(f"worker {worker} never started beating: "
                       f"{sup.events} {box}")


def _event(sup, type_, **match):
    return next((e for e in sup.events if e["type"] == type_
                 and all(e.get(k) == v for k, v in match.items())), None)


def bench_kill(tmp) -> dict:
    # sweeps slowed to ~0.25s so the kill lands genuinely mid-run
    sup = _gang(tmp, "kill", per_worker_env=lambda a, i: (
        {"PIO_FAULT_SPEC": "train.sweep:latency:1000:0.25"}
        if i == 0 and a == 0 else {}))
    t, box = _run_async(sup)
    pid = _wait_first_beat(sup, box, worker=1, attempt=0)
    t_kill = time.time()
    os.kill(pid, signal.SIGKILL)
    log(f"[gang-bench] SIGKILLed worker 1 (pid {pid})")
    t.join(timeout=900)
    if t.is_alive() or box.get("outcome") != COMPLETED:
        raise RuntimeError(f"kill bracket did not complete: {box} "
                           f"{sup.events}")
    fail = _event(sup, "failure", reason="exit")
    relaunch = _event(sup, "gangStart", attempt=1)
    done = _event(sup, "completed")
    assert fail and relaunch and done, sup.events
    return {
        "detect_kill_ms": round((fail["t"] - t_kill) * 1000, 1),
        "relaunch_ms": round((relaunch["t"] - fail["t"]) * 1000, 1),
        "recover_to_done_ms": round((done["t"] - relaunch["t"]) * 1000, 1),
        "restarts": sup.restarts,
    }


def bench_stall(tmp) -> dict:
    sup = _gang(tmp, "stall", per_worker_env=lambda a, i: (
        {"PIO_FAULT_SPEC": "train.sweep:latency:1000:0.25"}
        if i == 0 and a <= 1 else {}))
    t, box = _run_async(sup)
    pid = _wait_first_beat(sup, box, worker=1, attempt=0)
    t_stop = time.time()
    os.kill(pid, signal.SIGSTOP)
    log(f"[gang-bench] SIGSTOPped worker 1 (pid {pid})")
    t.join(timeout=900)
    if t.is_alive() or box.get("outcome") != COMPLETED:
        raise RuntimeError(f"stall bracket did not complete: {box} "
                           f"{sup.events}")
    fail = _event(sup, "failure", reason="stall")
    done = _event(sup, "completed")
    assert fail and done, sup.events
    # NOTE: stall age counts from the worker's last BEAT, which can
    # predate the SIGSTOP by up to a sweep — detect_stall_ms may land
    # slightly under the threshold. The bracket's point is that it is
    # O(threshold), not O(forever).
    detect = (fail["t"] - t_stop) * 1000
    return {
        "stall_threshold_ms": STALL_MS,
        "detect_stall_ms": round(detect, 1),
        "restarts": sup.restarts,
    }


def main() -> int:
    import tempfile

    results = {"num_workers": 2, "n_iters": N_ITERS}
    with tempfile.TemporaryDirectory(prefix="pio_gang_bench_") as tmp:
        t0 = time.time()
        log("[gang-bench] kill bracket ...")
        results["kill"] = bench_kill(tmp)
        if os.environ.get("PIO_GANG_BENCH_STALL", "1") != "0":
            log("[gang-bench] stall bracket ...")
            results["stall"] = bench_stall(tmp)
        results["bench_seconds"] = round(time.time() - t0, 1)

    # persist: BASELINE.json published bracket + the MULTICHIP file
    baseline_path = os.path.join(HERE, "BASELINE.json")
    try:
        with open(baseline_path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})["measured_gang_recovery"] = results
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=2)
    except Exception as e:  # noqa: BLE001 - bench must still print
        log(f"[gang-bench] could not persist to BASELINE.json: {e}")
    with open(os.path.join(HERE, "MULTICHIP_gang.json"), "w") as f:
        json.dump({"metric": "gang supervised recovery (2 workers, "
                             "sharded ALS, CPU gloo)", **results}, f,
                  indent=2)

    print(json.dumps({
        "metric": "gang kill detect/relaunch/recover ms",
        "value": [results["kill"]["detect_kill_ms"],
                  results["kill"]["relaunch_ms"],
                  results["kill"]["recover_to_done_ms"]],
        **({"stall_detect_ms": results["stall"]["detect_stall_ms"]}
           if "stall" in results else {}),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
