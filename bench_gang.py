"""Gang-restart recovery bench: how fast does supervised multi-worker
training detect a dead/hung worker and resume from checkpoint?

Runs a REAL 2-process sharded-ALS gang (tests/gang_als_worker.py) under
parallel/supervisor.Supervisor and measures, with wall-clock brackets:

- kill bracket: SIGKILL one worker mid-training →
  ``detect_kill_ms`` (death → supervisor failure event),
  ``relaunch_ms`` (failure → relaunched gang, incl. jittered backoff),
  ``recover_to_done_ms`` (relaunch → training complete).
- stall bracket (``PIO_GANG_BENCH_STALL=0`` skips): SIGSTOP one worker →
  ``detect_stall_ms`` (stop → failure event; dominated by the
  configured ``PIO_WORKER_STALL_MS``, reported alongside it so the
  detector overhead is visible).

Like every bench here: same-run brackets only — this host's CPU varies
wildly run to run (BASELINE.md), so the numbers are for shape, not
absolutes. Results print as one JSON line and persist under
``BASELINE.json.published.measured_gang_recovery`` plus
``MULTICHIP_gang.json`` (the multichip bracket the roadmap asks for).
"""

import json
import os
import signal
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from incubator_predictionio_tpu.parallel.supervisor import (  # noqa: E402
    COMPLETED,
    GangConfig,
    Supervisor,
)

WORKER = os.path.join(HERE, "tests", "gang_als_worker.py")
N_ITERS = 8
STALL_MS = 6000.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _gang(tmp, tag, per_worker_env=None, max_restarts=3):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_COMPILATION_CACHE_DIR": os.path.join(tmp, "xla_cache"),
    }
    env.pop("PIO_FAULT_SPEC", None)
    return Supervisor(
        [sys.executable, WORKER, os.path.join(tmp, f"{tag}.npz"),
         os.path.join(tmp, f"ckpt_{tag}"), str(N_ITERS)],
        num_workers=2, env=env, per_worker_env=per_worker_env,
        config=GangConfig(num_workers=2, heartbeat_ms=250.0,
                          stall_ms=STALL_MS, init_grace_ms=300_000.0,
                          max_restarts=max_restarts, poll_ms=50.0),
        run_dir=os.path.join(tmp, f"run_{tag}"),
    )


def _run_async(sup):
    box = {}
    t = threading.Thread(
        target=lambda: box.update(outcome=sup.run()), daemon=True)
    t.start()
    return t, box


def _wait_first_beat(sup, box, worker=1, attempt=0, timeout=600):
    """Block until `worker` of `attempt` starts beating (mid-training),
    then return its pid."""
    deadline = time.monotonic() + timeout
    hb = os.path.join(sup.run_dir, f"worker_{worker}.hb")
    while time.monotonic() < deadline and not box:
        start = next((e for e in list(sup.events)
                      if e["type"] == "gangStart"
                      and e["attempt"] == attempt), None)
        if start and os.path.exists(hb):
            return start["pids"][worker]
        time.sleep(0.02)
    raise RuntimeError(f"worker {worker} never started beating: "
                       f"{sup.events} {box}")


def _event(sup, type_, **match):
    return next((e for e in sup.events if e["type"] == type_
                 and all(e.get(k) == v for k, v in match.items())), None)


def bench_kill(tmp) -> dict:
    # sweeps slowed to ~0.25s so the kill lands genuinely mid-run
    sup = _gang(tmp, "kill", per_worker_env=lambda a, i: (
        {"PIO_FAULT_SPEC": "train.sweep:latency:1000:0.25"}
        if i == 0 and a == 0 else {}))
    t, box = _run_async(sup)
    pid = _wait_first_beat(sup, box, worker=1, attempt=0)
    t_kill = time.time()
    os.kill(pid, signal.SIGKILL)
    log(f"[gang-bench] SIGKILLed worker 1 (pid {pid})")
    t.join(timeout=900)
    if t.is_alive() or box.get("outcome") != COMPLETED:
        raise RuntimeError(f"kill bracket did not complete: {box} "
                           f"{sup.events}")
    fail = _event(sup, "failure", reason="exit")
    relaunch = _event(sup, "gangStart", attempt=1)
    done = _event(sup, "completed")
    assert fail and relaunch and done, sup.events
    return {
        "detect_kill_ms": round((fail["t"] - t_kill) * 1000, 1),
        "relaunch_ms": round((relaunch["t"] - fail["t"]) * 1000, 1),
        "recover_to_done_ms": round((done["t"] - relaunch["t"]) * 1000, 1),
        "restarts": sup.restarts,
    }


def bench_stall(tmp) -> dict:
    sup = _gang(tmp, "stall", per_worker_env=lambda a, i: (
        {"PIO_FAULT_SPEC": "train.sweep:latency:1000:0.25"}
        if i == 0 and a <= 1 else {}))
    t, box = _run_async(sup)
    pid = _wait_first_beat(sup, box, worker=1, attempt=0)
    t_stop = time.time()
    os.kill(pid, signal.SIGSTOP)
    log(f"[gang-bench] SIGSTOPped worker 1 (pid {pid})")
    t.join(timeout=900)
    if t.is_alive() or box.get("outcome") != COMPLETED:
        raise RuntimeError(f"stall bracket did not complete: {box} "
                           f"{sup.events}")
    fail = _event(sup, "failure", reason="stall")
    done = _event(sup, "completed")
    assert fail and done, sup.events
    # NOTE: stall age counts from the worker's last BEAT, which can
    # predate the SIGSTOP by up to a sweep — detect_stall_ms may land
    # slightly under the threshold. The bracket's point is that it is
    # O(threshold), not O(forever).
    detect = (fail["t"] - t_stop) * 1000
    return {
        "stall_threshold_ms": STALL_MS,
        "detect_stall_ms": round(detect, 1),
        "restarts": sup.restarts,
    }


# ---------------------------------------------------------------------------
# partition-feed bracket: feed-path A/B + training scale-out (ISSUE 15)
# ---------------------------------------------------------------------------

FEED_EVENTS = 120_000
FEED_SHARDS = 4
FEED_USERS, FEED_ITEMS = 3000, 1500


def _host_calibration() -> float:
    """Single-thread Python Mops (bench_ingest's common denominator)."""
    t0 = time.perf_counter()
    s = 0
    for i in range(2_000_000):
        s += i
    return 2.0 / (time.perf_counter() - t0)


def _build_feed_workspace(tmp: str) -> dict:
    """SQLITE metadata/models + a JSONL event log partitioned into
    FEED_SHARDS shards, every shard compacted then appended past the
    snapshot, plus the recommendation engine dir `pio train` loads."""
    import numpy as np

    from incubator_predictionio_tpu.data.api import event_log
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.data.storage.datamap import DataMap
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents
    from incubator_predictionio_tpu.data.storage.registry import Storage

    ws = os.path.join(tmp, "feed_ws")
    os.makedirs(ws)
    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "JL",
        "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
        "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(ws, "meta.sqlite"),
        "PIO_STORAGE_SOURCES_JL_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_JL_PATH": os.path.join(ws, "events"),
    }
    storage = Storage(env)
    storage.get_meta_data_apps().insert(App(id=1, name="feedbench"))
    events_dir = storage.get_l_events().events_dir
    rng = np.random.default_rng(20260804)
    per = FEED_EVENTS // FEED_SHARDS
    import datetime as dt

    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    for part in range(FEED_SHARDS):
        os.environ["PIO_EVENT_PARTITION"] = str(part)
        st = JSONLEvents(events_dir)
        u = rng.integers(0, FEED_USERS, per)
        it = rng.integers(0, FEED_ITEMS, per)
        r = rng.integers(1, 6, per)
        # compacted prefix (90%) + uncovered tail (10%)
        cut = int(per * 0.9)
        for lo, hi in ((0, cut), (cut, per)):
            st.insert_batch([
                Event(event="rate", entity_type="user",
                      entity_id=str(u[j]), target_entity_type="item",
                      target_entity_id=str(it[j]),
                      properties=DataMap({"rating": float(r[j])}),
                      event_time=t0)
                for j in range(lo, hi)], 1)
            if lo == 0:
                path = os.path.join(events_dir,
                                    f"events_1.p{part}.jsonl")
                assert event_log.compact_log(path)
    os.environ.pop("PIO_EVENT_PARTITION", None)
    engine_dir = os.path.join(ws, "engine")
    os.makedirs(engine_dir)
    with open(os.path.join(engine_dir, "engine.json"), "w") as f:
        json.dump({
            "id": "default",
            "engineFactory": "incubator_predictionio_tpu.models."
                             "recommendation.RecommendationEngine",
            "datasource": {"params": {"appName": "feedbench"}},
            "algorithms": [{"name": "", "params": {
                "rank": 8, "numIterations": 4, "lambda": 0.05,
                "seed": 5}}],
        }, f)
    return {"ws": ws, "env": env, "events_dir": events_dir,
            "engine_dir": engine_dir}


def bench_feed_ab(events_dir: str, rounds: int = 3) -> dict:
    """Same-run A/B/C: per-gang-worker training-read cost of
    (A) the partition-local colseg feed (this worker's shards only,
    snapshot prefix + tail parse, no merge), vs (B) the merged view
    (all shards, snapshot-seeded cold build + interning remap — what
    every gang worker used to pay), vs (C) the merged view with the
    snapshots hidden (pure JSON re-parse — the pre-compaction floor).
    Workers=2: A scans half the shards; B/C always scan all of them."""
    import shutil

    import numpy as np

    from incubator_predictionio_tpu.data.api import partition_feed as pf
    from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents

    def read_partition_feed() -> int:
        total = 0
        feed = pf.PartitionFeed(events_dir, 1, None, 0, 2)
        for path in feed.shard_list():
            shard = pf.scan_shard(path)
            sr = pf.PartitionFeed.shard_ratings(shard, ["rate", "buy"])
            total += len(sr.rating)
        return total

    def read_merged() -> int:
        st = JSONLEvents(events_dir)   # fresh: a train process is cold
        cols, rows = st.scan_columnar(1, None, ["rate", "buy"])
        return int(rows.size)

    manifests = [os.path.join(events_dir, n)
                 for n in os.listdir(events_dir)
                 if n.endswith(".manifest")]

    def read_merged_json() -> int:
        for m in manifests:   # hide the snapshots: force the re-parse
            shutil.move(m, m + ".hide")
        try:
            return read_merged()
        finally:
            for m in manifests:
                shutil.move(m + ".hide", m)

    t_a, t_b, t_c = [], [], []
    n_a = n_b = 0
    for _ in range(rounds):
        t0 = time.perf_counter()
        n_a = read_partition_feed()
        t_a.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        n_b = read_merged()
        t_b.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        read_merged_json()
        t_c.append(time.perf_counter() - t0)
    out = {
        "workers": 2,
        "shards": FEED_SHARDS,
        "events_total": FEED_EVENTS,
        "events_this_worker": n_a,
        "merged_rows": n_b,
        "partition_feed_worker_ms": round(
            float(np.median(t_a)) * 1000, 1),
        "merged_view_worker_ms": round(float(np.median(t_b)) * 1000, 1),
        "merged_json_reparse_worker_ms": round(
            float(np.median(t_c)) * 1000, 1),
        # within-round ratios, then median (host CPU swings within runs)
        "speedup_vs_merged": round(float(np.median(
            [b / a for a, b in zip(t_a, t_b)])), 2),
        "speedup_vs_merged_json": round(float(np.median(
            [c / a for a, c in zip(t_a, t_c)])), 2),
    }
    log(f"[gang-bench] feed A/B: {out}")
    return out


def _run_train(env: dict, engine_dir: str, num_workers: int,
               tmp: str) -> float:
    argv = [sys.executable, "-m",
            "incubator_predictionio_tpu.tools.console", "train",
            "--engine-dir", engine_dir]
    if num_workers > 1:
        argv += ["--num-workers", str(num_workers)]
    run_env = {
        **os.environ, **env,
        "PIO_TRAIN_FEED": "partition",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_COMPILATION_CACHE_DIR": os.path.join(tmp, "xla_cache"),
    }
    run_env.pop("PIO_FAULT_SPEC", None)
    t0 = time.perf_counter()
    proc = __import__("subprocess").run(
        argv, env=run_env, capture_output=True, text=True, timeout=900)
    wall = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(
            f"train --num-workers {num_workers} rc={proc.returncode}: "
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return wall


def bench_feed_scaling(ws: dict, tmp: str, rounds: int = 2) -> dict:
    """REAL `pio train --num-workers N` wall-clock, 1/2/4 workers,
    same-run interleaved rounds; speedups are medians of WITHIN-round
    ratios (PR 8 precedent — this host's CPU swings severalfold inside
    one run)."""
    import numpy as np

    walls = {1: [], 2: [], 4: []}
    _run_train(ws["env"], ws["engine_dir"], 1, tmp)  # compile warm-up
    for rnd in range(rounds):
        for n in (1, 2, 4):
            w = _run_train(ws["env"], ws["engine_dir"], n, tmp)
            walls[n].append(w)
            log(f"[gang-bench] round {rnd} train x{n}: {w:.1f}s")
    out = {"rounds": rounds}
    for n in (1, 2, 4):
        out[f"train_wall_s_{n}"] = round(float(np.median(walls[n])), 1)
    for n in (2, 4):
        out[f"speedup_{n}"] = round(float(np.median(
            [w1 / wn for w1, wn in zip(walls[1], walls[n])])), 2)
    if out["speedup_2"] < 1.0:
        out["note"] = (
            "end-to-end gang wall at bench scale is dominated by "
            "per-process fixed costs (jax import + distributed init + "
            "compile, ~10s each here) and per-iteration gloo "
            "collectives, not by the data work the feed splits — the "
            "ceiling control shows whether the HOST could overlap "
            "processes; the feed A/B above is the per-worker axis "
            "that scales with data volume")
    return out


def bench_feed_ceiling(ws: dict, tmp: str) -> dict:
    """Host scale-out ceiling control: TWO fully independent
    single-process trains run concurrently vs one alone — the best any
    2-worker architecture could do on this host. 1.0 = two fit for
    free; 0.5 = fully serialized cores."""
    import concurrent.futures as cf
    import shutil

    import numpy as np

    # a second, fully independent workspace (same data): concurrent
    # trains must not share a sqlite file or an engine group
    ws2 = os.path.join(tmp, "feed_ws2")
    shutil.copytree(ws["ws"], ws2)
    env2 = {**ws["env"],
            "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(
                ws2, "meta.sqlite"),
            "PIO_STORAGE_SOURCES_JL_PATH": os.path.join(ws2, "events")}
    eng2 = os.path.join(ws2, "engine")

    one = _run_train(ws["env"], ws["engine_dir"], 1, tmp)
    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(2) as pool:
        f1 = pool.submit(_run_train, ws["env"], ws["engine_dir"], 1, tmp)
        f2 = pool.submit(_run_train, env2, eng2, 1, tmp)
        f1.result()
        f2.result()
    pair = time.perf_counter() - t0
    out = {"one_train_s": round(one, 1),
           "two_concurrent_trains_s": round(pair, 1),
           "ceiling": round(float(np.median([one / pair])), 2)}
    if out["ceiling"] < 0.9:
        out["note"] = (
            "host-limited: two independent trains cannot run "
            "concurrently for free on this box — scale-out speedups "
            "above are bounded by the host, not the architecture "
            "(PR 3/8 precedent)")
    log(f"[gang-bench] ceiling control: {out}")
    return out


def bench_feed(tmp: str) -> dict:
    ws = _build_feed_workspace(tmp)
    results = {
        "events": FEED_EVENTS,
        "shards": FEED_SHARDS,
        "host_loop_mops": round(_host_calibration(), 1),
        "feed_ab": bench_feed_ab(ws["events_dir"]),
    }
    if os.environ.get("PIO_GANG_BENCH_SCALING", "1") != "0":
        results["scaling"] = bench_feed_scaling(ws, tmp)
        results["host_scaleout_ceiling"] = bench_feed_ceiling(ws, tmp)
    return results


def main() -> int:
    import tempfile

    results = {"num_workers": 2, "n_iters": N_ITERS}
    feed_results = None
    with tempfile.TemporaryDirectory(prefix="pio_gang_bench_") as tmp:
        t0 = time.time()
        log("[gang-bench] kill bracket ...")
        results["kill"] = bench_kill(tmp)
        if os.environ.get("PIO_GANG_BENCH_STALL", "1") != "0":
            log("[gang-bench] stall bracket ...")
            results["stall"] = bench_stall(tmp)
        if os.environ.get("PIO_GANG_BENCH_FEED", "1") != "0":
            log("[gang-bench] partition-feed bracket ...")
            t_feed = time.time()
            feed_results = bench_feed(tmp)
            feed_results["bench_seconds"] = round(time.time() - t_feed, 1)
        results["bench_seconds"] = round(time.time() - t0, 1)

    # persist: BASELINE.json published brackets + the MULTICHIP file
    baseline_path = os.path.join(HERE, "BASELINE.json")
    try:
        with open(baseline_path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})["measured_gang_recovery"] = results
        if feed_results is not None:
            doc["published"]["measured_gang_feed"] = feed_results
        with open(baseline_path, "w") as f:
            json.dump(doc, f, indent=2)
    except Exception as e:  # noqa: BLE001 - bench must still print
        log(f"[gang-bench] could not persist to BASELINE.json: {e}")
    with open(os.path.join(HERE, "MULTICHIP_gang.json"), "w") as f:
        json.dump({"metric": "gang supervised recovery (2 workers, "
                             "sharded ALS, CPU gloo) + partition-local "
                             "training feeds (1/2/4-worker bracket, "
                             "feed-path A/B, ceiling control)",
                   **results,
                   **({"feed": feed_results}
                      if feed_results is not None else {})}, f,
                  indent=2)

    print(json.dumps({
        "metric": "gang kill detect/relaunch/recover ms",
        "value": [results["kill"]["detect_kill_ms"],
                  results["kill"]["relaunch_ms"],
                  results["kill"]["recover_to_done_ms"]],
        **({"stall_detect_ms": results["stall"]["detect_stall_ms"]}
           if "stall" in results else {}),
        **({"feed_speedup_vs_merged":
            feed_results["feed_ab"]["speedup_vs_merged"],
            "feed_speedup_vs_merged_json":
            feed_results["feed_ab"]["speedup_vs_merged_json"]}
           if feed_results is not None else {}),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
