// libpioevent — native event-log codec: JSONL → columnar arrays.
//
// Plays the role the HBase client + Spark TableInputFormat scan play in the
// reference (storage/hbase/.../HBPEvents.scala: the bulk "RDD[Event]" read
// path): a scan-optimized event store of record. Here the store is an
// append-only JSONL log and the scan is this parser, which decodes event
// JSON straight into interned id codes + timestamps + ratings — the exact
// columnar layout the TPU input pipeline uploads — without materializing
// per-event Python objects.
//
// C ABI (ctypes-friendly); no external dependencies; C++17.
//
// Record layout produced per event:
//   event/etype/eid/tetype/teid : int32 codes into interned string tables
//                                 (tetype/teid = -1 when absent)
//   time_us                     : int64 epoch microseconds (INT64_MIN absent)
//   rating                      : float32 properties.rating
//                                 (NaN = key absent; -inf = key present but
//                                 not coercible to a finite number — the
//                                 two cases fill differently upstream)
//   props[2n]                   : byte offsets [start,end) of the raw
//                                 properties JSON object (-1,-1 absent)
//   span[2n]                    : byte offsets [start,end) of the whole
//                                 event object (lazy single-event reparse)
//   event_id                    : int32 code into table 5 (-1 absent)
//
// Tombstone records {"__tombstone__": "<eventId>"} are collected separately
// together with their position (count of event records parsed before the
// tombstone) so deletes only affect records appended BEFORE them — a
// re-insert after a delete is live again, matching the upsert backends.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <algorithm>
#include <vector>

namespace {

struct Interner {
  std::unordered_map<std::string, int32_t> map;
  std::vector<std::string> table;

  int32_t intern(std::string&& s) {
    auto it = map.find(s);
    if (it != map.end()) return it->second;
    int32_t id = static_cast<int32_t>(table.size());
    map.emplace(s, id);
    table.push_back(std::move(s));
    return id;
  }
};

constexpr int kNumTables = 6;  // event, etype, eid, tetype, teid, eventId

struct Columns {
  std::vector<int32_t> event, etype, eid, tetype, teid, event_id;
  std::vector<int64_t> time_us;
  std::vector<float> rating;
  std::vector<int64_t> props;  // 2n offsets
  std::vector<int64_t> span;   // 2n offsets
  Interner tables[kNumTables];
  std::vector<std::string> tombstones;
  std::vector<int64_t> tombstone_pos;  // records parsed before each tombstone
};

struct Parser {
  const char* base;
  const char* p;
  const char* end;
  std::string err;
  int64_t n_records = 0;

  explicit Parser(const char* buf, int64_t len)
      : base(buf), p(buf), end(buf + len) {}

  bool fail(const char* msg) {
    if (err.empty()) {
      char tmp[160];
      snprintf(tmp, sizeof tmp, "%s at byte %lld (record %lld)", msg,
               static_cast<long long>(p - base),
               static_cast<long long>(n_records));
      err = tmp;
    }
    return false;
  }

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool at_end() {
    ws();
    return p >= end;
  }

  // Decode a JSON string (cursor on opening quote) into out.
  bool parse_string(std::string& out) {
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    out.clear();
    while (p < end) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        ++p;
        return true;
      }
      if (c == '\\') {
        if (p + 1 >= end) return fail("bad escape");
        ++p;
        switch (*p) {
          case '"': out += '"'; ++p; break;
          case '\\': out += '\\'; ++p; break;
          case '/': out += '/'; ++p; break;
          case 'b': out += '\b'; ++p; break;
          case 'f': out += '\f'; ++p; break;
          case 'n': out += '\n'; ++p; break;
          case 'r': out += '\r'; ++p; break;
          case 't': out += '\t'; ++p; break;
          case 'u': {
            ++p;
            unsigned cp;
            if (!hex4(cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              if (p + 1 < end && p[0] == '\\' && p[1] == 'u') {
                p += 2;
                unsigned lo;
                if (!hex4(lo)) return false;
                if (lo >= 0xDC00 && lo <= 0xDFFF)
                  cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                else
                  cp = 0xFFFD;
              } else {
                cp = 0xFFFD;
              }
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              cp = 0xFFFD;
            }
            append_utf8(out, cp);
            break;
          }
          default:
            return fail("bad escape");
        }
      } else {
        out += static_cast<char>(c);
        ++p;
      }
    }
    return fail("unterminated string");
  }

  bool hex4(unsigned& out) {
    if (p + 4 > end) return fail("bad \\u escape");
    out = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p++;
      out <<= 4;
      if (c >= '0' && c <= '9') out |= c - '0';
      else if (c >= 'a' && c <= 'f') out |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') out |= c - 'A' + 10;
      else return fail("bad \\u escape");
    }
    return true;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  bool skip_string() {
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    while (p < end) {
      if (*p == '\\') {
        p += 2;
        continue;
      }
      if (*p == '"') {
        ++p;
        return true;
      }
      ++p;
    }
    return fail("unterminated string");
  }

  bool parse_number(double& out) {
    char* q = nullptr;
    out = strtod(p, &q);
    if (q == p) return fail("bad number");
    p = q;
    return true;
  }

  bool skip_value() {
    ws();
    if (p >= end) return fail("unexpected end");
    switch (*p) {
      case '"':
        return skip_string();
      case '{': {
        ++p;
        ws();
        if (p < end && *p == '}') { ++p; return true; }
        while (true) {
          ws();
          if (!skip_string()) return false;
          ws();
          if (p >= end || *p != ':') return fail("expected ':'");
          ++p;
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { ++p; continue; }
          if (p < end && *p == '}') { ++p; return true; }
          return fail("expected ',' or '}'");
        }
      }
      case '[': {
        ++p;
        ws();
        if (p < end && *p == ']') { ++p; return true; }
        while (true) {
          if (!skip_value()) return false;
          ws();
          if (p < end && *p == ',') { ++p; continue; }
          if (p < end && *p == ']') { ++p; return true; }
          return fail("expected ',' or ']'");
        }
      }
      case 't':
        if (end - p >= 4 && !memcmp(p, "true", 4)) { p += 4; return true; }
        return fail("bad literal");
      case 'f':
        if (end - p >= 5 && !memcmp(p, "false", 5)) { p += 5; return true; }
        return fail("bad literal");
      case 'n':
        if (end - p >= 4 && !memcmp(p, "null", 4)) { p += 4; return true; }
        return fail("bad literal");
      default: {
        double d;
        return parse_number(d);
      }
    }
  }

  // properties object: record raw span, extract top-level numeric "rating".
  bool parse_properties(int64_t& start, int64_t& stop, float& rating) {
    ws();
    if (p >= end) return fail("unexpected end");
    if (*p == 'n') {  // null
      if (end - p >= 4 && !memcmp(p, "null", 4)) {
        p += 4;
        start = stop = -1;
        return true;
      }
      return fail("bad literal");
    }
    if (*p != '{') return fail("properties must be an object");
    start = p - base;
    ++p;
    ws();
    if (p < end && *p == '}') {
      ++p;
      stop = p - base;
      return true;
    }
    std::string key;
    while (true) {
      ws();
      if (!parse_string(key)) return false;
      ws();
      if (p >= end || *p != ':') return fail("expected ':'");
      ++p;
      ws();
      bool is_num = p < end && (*p == '-' || (*p >= '0' && *p <= '9'));
      if (key == "rating" && is_num) {
        double d;
        if (!parse_number(d)) return false;
        // Finiteness is judged AFTER the float32 cast (fast/slow parity:
        // the row path's matrix is float32 too); 1e999-style overflow and
        // float32-range overflow are both "present but unusable".
        float f32 = static_cast<float>(d);
        rating = std::isfinite(f32) ? f32 : -INFINITY;
      } else if (key == "rating" && p < end && *p == '"') {
        // string-typed numeric rating (some SDK exports): coerce like the
        // row path's float() — full-string finite parse, else "present but
        // unusable" (-inf), which upstream fills with default_rating.
        // Charset pre-check: strtod accepts hex/inf/nan spellings that
        // Python's float() rejects (or that parse to non-finite anyway).
        std::string sval2;
        if (!parse_string(sval2)) return false;
        bool charset_ok = true;
        for (char ch : sval2) {
          // Exact whitespace set " \t\r\n" (NOT isspace: \v and \f are
          // accepted by strtod skipping but rejected by Python's float()).
          if (!((ch >= '0' && ch <= '9') || ch == '.' || ch == '+' ||
                ch == '-' || ch == 'e' || ch == 'E' || ch == ' ' ||
                ch == '\t' || ch == '\r' || ch == '\n')) {
            charset_ok = false;
            break;
          }
        }
        const char* b = sval2.c_str();
        char* e2 = nullptr;
        double d = charset_ok ? strtod(b, &e2) : 0.0;
        while (e2 && isspace(static_cast<unsigned char>(*e2))) ++e2;
        float f32 = static_cast<float>(d);
        if (charset_ok && e2 && e2 != b && *e2 == '\0' && std::isfinite(f32))
          rating = f32;
        else
          rating = -INFINITY;
      } else if (key == "rating") {
        // bool / null / object / array rating: present but unusable.
        if (!skip_value()) return false;
        rating = -INFINITY;
      } else {
        if (!skip_value()) return false;
      }
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') {
        ++p;
        stop = p - base;
        return true;
      }
      return fail("expected ',' or '}'");
    }
  }

  // ISO-8601 → epoch micros; INT64_MIN on parse failure.
  static int64_t parse_iso8601(const std::string& s) {
    const char* q = s.c_str();
    const char* qe = q + s.size();
    auto digits = [&](int n, long& out) -> bool {
      out = 0;
      for (int i = 0; i < n; ++i) {
        if (q >= qe || *q < '0' || *q > '9') return false;
        out = out * 10 + (*q++ - '0');
      }
      return true;
    };
    long Y, M, D, h = 0, m = 0;
    double sec = 0.0;
    if (!digits(4, Y)) return INT64_MIN;
    if (q >= qe || *q != '-') return INT64_MIN;
    ++q;
    if (!digits(2, M)) return INT64_MIN;
    if (q >= qe || *q != '-') return INT64_MIN;
    ++q;
    if (!digits(2, D)) return INT64_MIN;
    if (q < qe && (*q == 'T' || *q == ' ')) {
      ++q;
      if (!digits(2, h)) return INT64_MIN;
      if (q >= qe || *q != ':') return INT64_MIN;
      ++q;
      if (!digits(2, m)) return INT64_MIN;
      if (q < qe && *q == ':') {
        ++q;
        long ss;
        if (!digits(2, ss)) return INT64_MIN;
        sec = static_cast<double>(ss);
        if (q < qe && *q == '.') {
          ++q;
          double scale = 0.1;
          while (q < qe && *q >= '0' && *q <= '9') {
            sec += (*q++ - '0') * scale;
            scale *= 0.1;
          }
        }
      }
    }
    long off_sec = 0;
    if (q < qe) {
      if (*q == 'Z') {
        ++q;
      } else if (*q == '+' || *q == '-') {
        int sign = (*q == '-') ? -1 : 1;
        ++q;
        long oh, om = 0;
        if (!digits(2, oh)) return INT64_MIN;
        if (q < qe && *q == ':') ++q;
        if (q < qe && *q >= '0' && *q <= '9') {
          if (!digits(2, om)) return INT64_MIN;
        }
        off_sec = sign * (oh * 3600 + om * 60);
      } else {
        return INT64_MIN;
      }
    }
    if (q != qe) return INT64_MIN;
    if (M < 1 || M > 12 || D < 1 || D > 31) return INT64_MIN;
    // days-from-civil (Howard Hinnant's algorithm, public domain)
    long y = Y - (M <= 2);
    long era = (y >= 0 ? y : y - 399) / 400;
    unsigned long yoe = static_cast<unsigned long>(y - era * 400);
    unsigned long doy = (153 * (M + (M > 2 ? -3 : 9)) + 2) / 5 + D - 1;
    unsigned long doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    int64_t days = era * 146097 + static_cast<int64_t>(doe) - 719468;
    // integral seconds exact in int64; only the fraction goes through double
    int64_t whole = days * 86400 + h * 3600 + m * 60 - off_sec;
    return whole * 1000000 + static_cast<int64_t>(llround(sec * 1e6));
  }

  bool parse_event(Columns& c) {
    ws();
    if (p >= end || *p != '{') return fail("expected event object");
    int64_t rec_start = p - base;
    ++p;
    std::string key, sval;
    int32_t ev = -1, et = -1, ei = -1, tet = -1, tei = -1, eid_code = -1;
    int64_t t_us = INT64_MIN;
    float rating = NAN;
    int64_t pstart = -1, pstop = -1;
    bool tombstone = false;
    std::string tomb_id;

    ws();
    bool first = true;
    if (p < end && *p == '}') {
      ++p;
    } else {
      while (true) {
        ws();
        if (!parse_string(key)) return false;
        ws();
        if (p >= end || *p != ':') return fail("expected ':'");
        ++p;
        ws();
        if (key == "event") {
          if (!parse_string(sval)) return false;
          ev = c.tables[0].intern(std::move(sval));
        } else if (key == "entityType") {
          if (!parse_string(sval)) return false;
          et = c.tables[1].intern(std::move(sval));
        } else if (key == "entityId") {
          if (!parse_string(sval)) return false;
          ei = c.tables[2].intern(std::move(sval));
        } else if (key == "targetEntityType") {
          if (p < end && *p == 'n') {
            if (!skip_value()) return false;
          } else {
            if (!parse_string(sval)) return false;
            tet = c.tables[3].intern(std::move(sval));
          }
        } else if (key == "targetEntityId") {
          if (p < end && *p == 'n') {
            if (!skip_value()) return false;
          } else {
            if (!parse_string(sval)) return false;
            tei = c.tables[4].intern(std::move(sval));
          }
        } else if (key == "eventId") {
          if (!parse_string(sval)) return false;
          eid_code = c.tables[5].intern(std::move(sval));
        } else if (key == "eventTime") {
          if (!parse_string(sval)) return false;
          t_us = parse_iso8601(sval);
        } else if (key == "properties") {
          if (!parse_properties(pstart, pstop, rating)) return false;
        } else if (key == "__tombstone__") {
          if (!parse_string(sval)) return false;
          tombstone = true;
          tomb_id = sval;
        } else {
          if (!skip_value()) return false;  // prId, creationTime, unknown
        }
        first = false;
        ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == '}') { ++p; break; }
        return fail("expected ',' or '}'");
      }
    }
    (void)first;
    int64_t rec_stop = p - base;
    ++n_records;
    if (tombstone) {
      c.tombstones.push_back(std::move(tomb_id));
      c.tombstone_pos.push_back(static_cast<int64_t>(c.event.size()));
      return true;
    }
    c.event.push_back(ev);
    c.etype.push_back(et);
    c.eid.push_back(ei);
    c.tetype.push_back(tet);
    c.teid.push_back(tei);
    c.event_id.push_back(eid_code);
    c.time_us.push_back(t_us);
    c.rating.push_back(rating);
    c.props.push_back(pstart);
    c.props.push_back(pstop);
    c.span.push_back(rec_start);
    c.span.push_back(rec_stop);
    return true;
  }
};

struct Handle {
  Columns cols;
  std::string err;
  // lazily materialized bulk exports (one ctypes call per table instead of
  // one per string)
  std::string table_blob[kNumTables];
  std::vector<int64_t> table_offsets[kNumTables];
  bool table_packed[kNumTables] = {};

  void pack(int which) {
    if (table_packed[which]) return;
    auto& t = cols.tables[which].table;
    auto& blob = table_blob[which];
    auto& offs = table_offsets[which];
    size_t total = 0;
    for (auto& s : t) total += s.size();
    blob.reserve(total);
    offs.reserve(t.size() + 1);
    offs.push_back(0);
    for (auto& s : t) {
      blob += s;
      offs.push_back(static_cast<int64_t>(blob.size()));
    }
    table_packed[which] = true;
  }
};

}  // namespace

extern "C" {

// Bump when the ABI or semantics change — the Python wrapper rebuilds the
// cached .so when this does not match its expected version.
int32_t pio_codec_version() { return 18; }

namespace {
// FNV-1a over a byte range, continuing from a running state.
inline uint32_t fnv1a(uint32_t h, const char* p, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    h = (h ^ static_cast<unsigned char>(p[i])) * 16777619u;
  }
  return h;
}
constexpr uint32_t kFnvInit = 2166136261u;
inline bool is_token_byte(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '\'';
}
}  // namespace

// Term-frequency rows for the text-classification template: tokenize
// ([A-Za-z0-9']+ runs, ASCII-lowercased — the token class is pure ASCII
// so byte-level scanning matches codepoint-level exactly), FNV-1a-hash
// each token (and each " "-joined n-gram up to `ngram`) into n_features
// buckets, accumulate counts into the caller-zeroed [n_docs, n_features]
// row-major float32 matrix. Bit-identical to the Python fallback in
// ops/tfidf.py. `df` (optional, caller-zeroed [n_features] int64)
// accumulates document frequency — the count of docs whose row touched
// each bucket — for free during the fill, so the IDF fit needs no
// second full pass over the [N,D] matrix. Returns 0, or -1 on invalid
// offsets.
namespace {
// Tokenize one doc's byte range and append the hashed bucket id of
// EVERY token occurrence (unigrams, then each n-gram order) to `out`.
// The ONE source of truth for the token byte class, lowercasing, and
// FNV-1a hashing — the dense and COO fills below differ only in how
// they consume this stream, which is what keeps them bit-identical.
inline void hash_doc_tokens(const char* buf, int64_t b0, int64_t b1,
                            uint32_t nf, int32_t ngram,
                            std::vector<char>& low,
                            std::vector<int64_t>& tok_s,
                            std::vector<int64_t>& tok_e,
                            std::vector<uint32_t>& out) {
  low.clear();
  tok_s.clear();
  tok_e.clear();
  out.clear();
  low.reserve(b1 - b0);
  bool in_tok = false;
  for (int64_t p = b0; p < b1; ++p) {
    unsigned char c = static_cast<unsigned char>(buf[p]);
    if (is_token_byte(c)) {
      if (!in_tok) {
        tok_s.push_back(static_cast<int64_t>(low.size()));
        in_tok = true;
      }
      low.push_back(c >= 'A' && c <= 'Z' ? c + 32 : c);
    } else if (in_tok) {
      tok_e.push_back(static_cast<int64_t>(low.size()));
      in_tok = false;
    }
  }
  if (in_tok) tok_e.push_back(static_cast<int64_t>(low.size()));
  // n_features is 4096 by default — mask instead of divide when pow2
  const uint32_t mask = (nf & (nf - 1)) == 0 ? nf - 1 : 0;
  const int64_t nt = static_cast<int64_t>(tok_s.size());
  for (int64_t j = 0; j < nt; ++j) {
    uint32_t h = fnv1a(kFnvInit, low.data() + tok_s[j], tok_e[j] - tok_s[j]);
    out.push_back(mask ? (h & mask) : (h % nf));
  }
  for (int32_t n = 2; n <= ngram; ++n) {
    for (int64_t j = 0; j + n <= nt; ++j) {
      uint32_t h = kFnvInit;
      for (int32_t q = 0; q < n; ++q) {
        if (q) h = (h ^ static_cast<uint32_t>(' ')) * 16777619u;
        h = fnv1a(h, low.data() + tok_s[j + q], tok_e[j + q] - tok_s[j + q]);
      }
      out.push_back(mask ? (h & mask) : (h % nf));
    }
  }
}
}  // namespace

int32_t pio_tfidf_tf(const char* buf, const int64_t* offs, int64_t n_docs,
                     int32_t n_features, int32_t ngram, float* out,
                     int64_t* df) {
  if (n_features <= 0 || ngram < 1) return -1;
  std::vector<char> low;
  std::vector<int64_t> tok_s;
  std::vector<int64_t> tok_e;
  std::vector<uint32_t> hashes;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int64_t b0 = offs[d], b1 = offs[d + 1];
    if (b0 < 0 || b1 < b0) return -1;
    hash_doc_tokens(buf, b0, b1, static_cast<uint32_t>(n_features), ngram,
                    low, tok_s, tok_e, hashes);
    float* row = out + d * static_cast<int64_t>(n_features);
    for (uint32_t idx : hashes) {
      if (df != nullptr && row[idx] == 0.0f) df[idx]++;
      row[idx] += 1.0f;
    }
  }
  return 0;
}

// COO variant of pio_tfidf_tf: per-doc (feature, count) pairs instead
// of dense [N, D] rows — the linear trainers reduce over docs anyway,
// so the dense matrix (which at corpus scale dwarfs the token stream:
// ~150 distinct buckets/doc vs D=4096 columns) never needs to exist,
// on the host or across the accelerator link. Same tokenizer, same
// FNV-1a hashing, same df semantics as the dense fill (bit-identical
// counts). doc_ptr is [n_docs+1] (CSR-style row pointers); feat/cnt
// receive up to `cap` entries. Returns nnz, -1 on invalid offsets, -2
// when cap is too small (caller bounds cap by the token-occurrence
// count, which nnz can never exceed).
int64_t pio_tfidf_tf_coo(const char* buf, const int64_t* offs,
                         int64_t n_docs, int32_t n_features, int32_t ngram,
                         int64_t cap, int64_t* doc_ptr, int32_t* feat_out,
                         float* cnt_out, int64_t* df) {
  if (n_features <= 0 || ngram < 1) return -1;
  std::vector<char> low;
  std::vector<int64_t> tok_s;
  std::vector<int64_t> tok_e;
  std::vector<uint32_t> hashes;
  std::vector<float> row(static_cast<size_t>(n_features), 0.0f);
  std::vector<int32_t> touched;
  int64_t nnz = 0;
  doc_ptr[0] = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int64_t b0 = offs[d], b1 = offs[d + 1];
    if (b0 < 0 || b1 < b0) return -1;
    hash_doc_tokens(buf, b0, b1, static_cast<uint32_t>(n_features), ngram,
                    low, tok_s, tok_e, hashes);
    touched.clear();
    for (uint32_t idx : hashes) {
      if (row[idx] == 0.0f) touched.push_back(static_cast<int32_t>(idx));
      row[idx] += 1.0f;
    }
    if (nnz + static_cast<int64_t>(touched.size()) > cap) return -2;
    // emission order: ascending bucket id (deterministic regardless of
    // token order; the Python fallback sorts to match)
    std::sort(touched.begin(), touched.end());
    for (int32_t idx : touched) {
      feat_out[nnz] = idx;
      cnt_out[nnz] = row[idx];
      if (df != nullptr) df[idx]++;
      row[idx] = 0.0f;
      ++nnz;
    }
    doc_ptr[d + 1] = nnz;
  }
  return nnz;
}

// Layout fill for ops/rowblocks.fill_buckets: scatter nnz COO entries
// into the planned bucket slabs in one sequential pass. Replaces the
// numpy path's stable argsort + position arithmetic (the dominant host
// cost of ALS layout prep); order within a row is the original entry
// order, bit-identical to the numpy fallback. `val`/`flat_vals` may be
// NULL together (binary-ratings mode: the value slabs are synthesized
// on device, so neither building nor uploading them is needed).
// Returns 0 on success, -1 col out of range, -2 computed destination
// out of range (corrupt / inconsistent plan tables), -3 row out of range.
int32_t pio_fill_entries(
    const int64_t* row, const int64_t* col, const float* val, int64_t nnz,
    const int64_t* col_slot_map, int64_t n_cols,
    const int64_t* prim_base, const int64_t* v_base, const int64_t* vc_e,
    int64_t* cursor, int64_t n_rows,
    int32_t* flat_cols, float* flat_vals, int64_t total) {
  for (int64_t r = 0; r < n_rows; ++r) cursor[r] = 0;
  for (int64_t i = 0; i < nnz; ++i) {
    const int64_t r = row[i];
    const int64_t c = col[i];
    if (r < 0 || r >= n_rows) return -3;
    if (c < 0 || c >= n_cols) return -1;
    const int64_t p = cursor[r]++;
    const int64_t ve = vc_e[r];
    const int64_t dest = p < ve ? v_base[r] + p : prim_base[r] + p - ve;
    if (dest < 0 || dest >= total) return -2;
    flat_cols[dest] = static_cast<int32_t>(col_slot_map[c]);
    if (flat_vals != nullptr) flat_vals[dest] = val[i];
  }
  return 0;
}

void* pio_parse_events_jsonl(const char* buf, int64_t len, char* errbuf,
                             int64_t errcap) {
  auto* h = new Handle();
  Parser parser(buf, len);
  while (!parser.at_end()) {
    if (!parser.parse_event(h->cols)) {
      if (errbuf && errcap > 0) {
        snprintf(errbuf, static_cast<size_t>(errcap), "%s",
                 parser.err.c_str());
      }
      delete h;
      return nullptr;
    }
  }
  return h;
}

static Handle* H(void* h) { return static_cast<Handle*>(h); }

int64_t pio_col_count(void* h) {
  return static_cast<int64_t>(H(h)->cols.event.size());
}
const int32_t* pio_col_event(void* h) { return H(h)->cols.event.data(); }
const int32_t* pio_col_etype(void* h) { return H(h)->cols.etype.data(); }
const int32_t* pio_col_eid(void* h) { return H(h)->cols.eid.data(); }
const int32_t* pio_col_tetype(void* h) { return H(h)->cols.tetype.data(); }
const int32_t* pio_col_teid(void* h) { return H(h)->cols.teid.data(); }
const int32_t* pio_col_event_id(void* h) { return H(h)->cols.event_id.data(); }
const int64_t* pio_col_time_us(void* h) { return H(h)->cols.time_us.data(); }
const float* pio_col_rating(void* h) { return H(h)->cols.rating.data(); }
const int64_t* pio_col_props(void* h) { return H(h)->cols.props.data(); }
const int64_t* pio_col_span(void* h) { return H(h)->cols.span.data(); }

int32_t pio_table_size(void* h, int32_t which) {
  if (which < 0 || which >= kNumTables) return -1;
  return static_cast<int32_t>(H(h)->cols.tables[which].table.size());
}

const char* pio_table_get(void* h, int32_t which, int32_t idx,
                          int32_t* len_out) {
  if (which < 0 || which >= kNumTables) return nullptr;
  auto& t = H(h)->cols.tables[which].table;
  if (idx < 0 || static_cast<size_t>(idx) >= t.size()) return nullptr;
  if (len_out) *len_out = static_cast<int32_t>(t[idx].size());
  return t[idx].data();
}

// Bulk table export: concatenated UTF-8 strings + (size+1) end offsets.
const char* pio_table_blob(void* h, int32_t which, int64_t* blob_len) {
  if (which < 0 || which >= kNumTables) return nullptr;
  Handle* hh = H(h);
  hh->pack(which);
  if (blob_len) *blob_len = static_cast<int64_t>(hh->table_blob[which].size());
  return hh->table_blob[which].data();
}

const int64_t* pio_table_offsets(void* h, int32_t which) {
  if (which < 0 || which >= kNumTables) return nullptr;
  Handle* hh = H(h);
  hh->pack(which);
  return hh->table_offsets[which].data();
}

int64_t pio_tombstone_count(void* h) {
  return static_cast<int64_t>(H(h)->cols.tombstones.size());
}

const int64_t* pio_tombstone_pos(void* h) {
  return H(h)->cols.tombstone_pos.data();
}

const char* pio_tombstone_get(void* h, int64_t idx, int32_t* len_out) {
  auto& t = H(h)->cols.tombstones;
  if (idx < 0 || static_cast<size_t>(idx) >= t.size()) return nullptr;
  if (len_out) *len_out = static_cast<int32_t>(t[idx].size());
  return t[idx].data();
}

void pio_free(void* h) { delete H(h); }

}  // extern "C"

// ===========================================================================
// Ingest fast path: validate + canonicalize a /batch/events.json body in one
// pass (reference hot path: data/.../data/api/EventServer.scala — POST →
// validate → store Put). The Python event server calls this with the RAW
// request bytes; on all_ok it appends the returned canonical JSONL straight
// to the event log without constructing a single Python Event. Any anomaly
// (validation failure, client-supplied eventId, over-cap count, top-level
// syntax error) flips all_ok off and the server falls back wholesale to the
// Python path, which produces the exact per-item error messages — so the C
// path only ever handles the uniform happy case, and semantics stay pinned
// by the Python implementation and its tests.
// ===========================================================================

namespace {

struct IngestOut {
  std::string lines;   // canonical JSONL for every item (valid only)
  int64_t n_items = 0;
  bool all_ok = true;
  std::string err;     // top-level parse error ("" when the array parsed)
};

// epoch micros → canonical "YYYY-MM-DDTHH:MM:SS.mmmZ" (millis TRUNCATED,
// matching Python format_event_time's microsecond//1000).
inline void format_us(int64_t us, std::string& out) {
  int64_t days = us / 86400000000LL;
  int64_t rem = us % 86400000000LL;
  if (rem < 0) { rem += 86400000000LL; days -= 1; }
  // civil-from-days (Howard Hinnant, public domain)
  int64_t z = days + 719468;
  int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  unsigned long doe = static_cast<unsigned long>(z - era * 146097);
  unsigned long yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  int64_t y = static_cast<int64_t>(yoe) + era * 400;
  unsigned long doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  unsigned long mp = (5 * doy + 2) / 153;
  unsigned long d = doy - (153 * mp + 2) / 5 + 1;
  unsigned long m = mp + (mp < 10 ? 3 : -9);
  y += (m <= 2);
  int64_t secs = rem / 1000000;
  int ms = static_cast<int>((rem % 1000000) / 1000);
  char tmp[32];
  snprintf(tmp, sizeof tmp, "%04lld-%02lu-%02luT%02lld:%02lld:%02lld.%03dZ",
           static_cast<long long>(y), m, d,
           static_cast<long long>(secs / 3600),
           static_cast<long long>((secs / 60) % 60),
           static_cast<long long>(secs % 60), ms);
  out += tmp;
}

struct IngestParser : Parser {
  using Parser::Parser;

  // -- STRICT JSON layer --------------------------------------------------
  // The ingest path persists raw byte spans verbatim, so anything the
  // lenient scan parser tolerates but Python's json.loads rejects
  // (leading '+', leading zeros, bare '.5'/'1.', raw control characters
  // in strings) MUST be refused here — a lenient accept would poison the
  // event log with records read-back cannot parse. Stricter-than-Python
  // is always safe: the caller falls back to the Python path.

  bool strict_string(std::string& out) {
    ws();
    if (p >= end || *p != '"') return false;
    const char* q = p + 1;
    bool esc = false;
    while (q < end) {
      unsigned char c = static_cast<unsigned char>(*q);
      if (c < 0x20) return false;  // python json: raw control chars invalid
      if (esc) esc = false;
      else if (c == '\\') esc = true;
      else if (c == '"') break;
      ++q;
    }
    bool ok = parse_string(out);
    if (!ok) err.clear();
    return ok;
  }

  bool strict_value() {
    ws();
    if (p >= end) return false;
    char c = *p;
    if (c == '"') { std::string s; return strict_string(s); }
    if (c == '{') {
      ++p; ws();
      if (p < end && *p == '}') { ++p; return true; }
      while (true) {
        ws();
        std::string k;
        if (!strict_string(k)) return false;
        ws();
        if (p >= end || *p++ != ':') return false;
        if (!strict_value()) return false;
        ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == '}') { ++p; return true; }
        return false;
      }
    }
    if (c == '[') {
      ++p; ws();
      if (p < end && *p == ']') { ++p; return true; }
      while (true) {
        if (!strict_value()) return false;
        ws();
        if (p < end && *p == ',') { ++p; continue; }
        if (p < end && *p == ']') { ++p; return true; }
        return false;
      }
    }
    if (c == 't') { if (end - p >= 4 && !memcmp(p, "true", 4)) { p += 4; return true; } return false; }
    if (c == 'f') { if (end - p >= 5 && !memcmp(p, "false", 5)) { p += 5; return true; } return false; }
    if (c == 'n') { if (end - p >= 4 && !memcmp(p, "null", 4)) { p += 4; return true; } return false; }
    // number per json grammar: -? (0|[1-9][0-9]*) (.[0-9]+)? ([eE][+-]?[0-9]+)?
    if (c == '-') ++p;
    if (p >= end) return false;
    if (*p == '0') ++p;
    else if (*p >= '1' && *p <= '9') { while (p < end && *p >= '0' && *p <= '9') ++p; }
    else return false;
    if (p < end && *p == '.') {
      ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    return true;
  }

  // Strict ISO-8601 with FULL range checks (Python fromisoformat parity
  // or narrower): hh<=23/mm,ss<=59, real day-of-month incl. leap years,
  // <=6 fractional digits, offset hh<=23/mm<=59, and the final UTC
  // instant inside Python's year 1..9999.
  static bool strict_iso_us(const std::string& s, int64_t& out_us) {
    const char* q = s.c_str();
    const char* qe = q + s.size();
    auto dig = [&](int n, long& v) -> bool {
      v = 0;
      for (int i = 0; i < n; ++i) {
        if (q >= qe || *q < '0' || *q > '9') return false;
        v = v * 10 + (*q++ - '0');
      }
      return true;
    };
    long Y, M, D, h = 0, m = 0, ss = 0, frac_us = 0;
    if (!dig(4, Y)) return false;
    if (q >= qe || *q++ != '-') return false;
    if (!dig(2, M)) return false;
    if (q >= qe || *q++ != '-') return false;
    if (!dig(2, D)) return false;
    if (Y < 1 || M < 1 || M > 12) return false;
    static const int mdays[] = {31,28,31,30,31,30,31,31,30,31,30,31};
    int md = mdays[M - 1] +
        ((M == 2 && (Y % 4 == 0 && (Y % 100 != 0 || Y % 400 == 0))) ? 1 : 0);
    if (D < 1 || D > md) return false;
    if (q < qe && (*q == 'T' || *q == ' ')) {
      ++q;
      if (!dig(2, h)) return false;
      if (q >= qe || *q++ != ':') return false;
      if (!dig(2, m)) return false;
      if (q < qe && *q == ':') {
        ++q;
        if (!dig(2, ss)) return false;
        if (q < qe && *q == '.') {
          ++q;
          int nd = 0;
          while (q < qe && *q >= '0' && *q <= '9') {
            if (nd >= 6) return false;  // >6 digits → python path decides
            frac_us = frac_us * 10 + (*q++ - '0');
            ++nd;
          }
          if (nd == 0) return false;
          while (nd < 6) { frac_us *= 10; ++nd; }
        }
      }
      if (h > 23 || m > 59 || ss > 59) return false;
    }
    long off = 0;
    if (q < qe) {
      if (*q == 'Z') ++q;
      else if (*q == '+' || *q == '-') {
        int sg = (*q == '-') ? -1 : 1;
        ++q;
        long oh, om = 0;
        if (!dig(2, oh)) return false;
        if (q < qe && *q == ':') { ++q; if (!dig(2, om)) return false; }
        else if (q < qe) { if (!dig(2, om)) return false; }
        if (oh > 23 || om > 59) return false;
        off = sg * (oh * 3600 + om * 60);
      } else return false;
    }
    if (q != qe) return false;
    long y = Y - (M <= 2);
    long era = (y >= 0 ? y : y - 399) / 400;
    unsigned long yoe = static_cast<unsigned long>(y - era * 400);
    unsigned long doy = (153 * (M + (M > 2 ? -3 : 9)) + 2) / 5 + D - 1;
    unsigned long doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
    int64_t days = era * 146097 + static_cast<int64_t>(doe) - 719468;
    int64_t us = (days * 86400 + h * 3600 + m * 60 + ss - off) * 1000000
                 + frac_us;
    // Python datetime years 1..9999 (UTC): outside → fallback
    if (us < -62135596800000000LL || us > 253402300799999999LL) return false;
    out_us = us;
    return true;
  }

  // Walk a JSON object value: capture its raw span, count keys, and check
  // the reserved "pio_" key prefix (decoded keys — escapes resolved).
  bool props_object(int64_t& start, int64_t& stop, int64_t& n_keys,
                    bool& pio_key) {
    ws();
    if (p >= end || *p != '{') return false;
    start = p - base;
    ++p;
    n_keys = 0;
    std::string key;
    ws();
    if (p < end && *p == '}') {
      ++p;
      stop = p - base;
      return true;
    }
    while (true) {
      ws();
      if (!strict_string(key)) return false;
      if (key.rfind("pio_", 0) == 0) pio_key = true;
      ++n_keys;
      ws();
      if (p >= end || *p != ':') return false;
      ++p;
      ws();
      if (!strict_value()) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; stop = p - base; return true; }
      return false;
    }
  }

  // Array of strings (tags); captures raw span.
  bool string_array(int64_t& start, int64_t& stop) {
    ws();
    if (p >= end || *p != '[') return false;
    start = p - base;
    ++p;
    std::string s;
    ws();
    if (p < end && *p == ']') { ++p; stop = p - base; return true; }
    while (true) {
      ws();
      if (!strict_string(s)) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; stop = p - base; return true; }
      return false;
    }
  }

  // String token: decoded value AND raw span (incl. quotes) for verbatim
  // re-serialization without re-escaping.
  bool string_token(std::string& out, int64_t& start, int64_t& stop) {
    ws();
    start = p - base;
    if (!strict_string(out)) return false;
    stop = p - base;
    return true;
  }

  // Integer token (ids may be JSON ints; floats/bools are invalid ids).
  bool int_token(int64_t& start, int64_t& stop) {
    ws();
    start = p - base;
    if (p < end && *p == '-') ++p;
    if (p >= end || *p < '0' || *p > '9') return false;
    while (p < end && *p >= '0' && *p <= '9') ++p;
    if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) return false;
    stop = p - base;
    return true;
  }

  bool is_null() { return p + 4 <= end && memcmp(p, "null", 4) == 0; }

  // One batch item → one canonical line appended to out.lines. ANY
  // anomaly (wrong type, failed validation, client eventId) sets
  // all_ok=false and stops — the Python path redoes the whole request,
  // so no recovery parsing is ever needed. Returns false only on
  // malformed JSON that also stops the scan.
  bool item(IngestOut& out, const char* id32, const std::string& creation) {
    ws();
    if (p >= end || *p != '{') return false;
    ++p;
    std::string ev, etype, key, sval, tet_val;
    int64_t ev_s = -1, ev_e = -1, et_s = -1, et_e = -1;
    int64_t ei_s = -1, ei_e = -1;       // entityId span (string or int)
    bool ei_int = false, ei_empty = true, has_ei = false;
    int64_t tet_s = -1, tet_e = -1, tei_s = -1, tei_e = -1;
    bool tei_int = false, tet_null = true, tei_null = true;
    int64_t pr_s = -1, pr_e = -1, pr_keys = 0;
    bool pio_key = false;
    int64_t tg_s = -1, tg_e = -1;
    int64_t prid_s = -1, prid_e = -1;
    int64_t t_us = INT64_MIN, d0 = 0, d1 = 0;
    bool has_time = false;
    // Duplicate-key guard (ADVICE r5): json.loads is last-wins, but the
    // single-pass state above is NOT safely overwritable (e.g. a second
    // null targetEntityType would leave tet_null=false from the first).
    // Any repeated known key forces the Python fallback, which produces
    // the exact last-wins semantics. Bit per known key:
    uint32_t seen_keys = 0;
    auto dup = [&](uint32_t bit) {
      bool already = seen_keys & bit;
      seen_keys |= bit;
      return already;
    };

    ws();
    if (p < end && *p == '}') {
      ++p;
      out.all_ok = false;  // missing required fields → python error path
      return true;
    }
    while (true) {
      ws();
      if (!strict_string(key)) return false;
      ws();
      if (p >= end || *p != ':') return false;
      ++p;
      if (key == "event") {
        if (dup(1u << 0)) { out.all_ok = false; return true; }
        if (!string_token(ev, ev_s, ev_e)) { out.all_ok = false; return true; }
      } else if (key == "entityType") {
        if (dup(1u << 1)) { out.all_ok = false; return true; }
        if (!string_token(etype, et_s, et_e)) { out.all_ok = false; return true; }
      } else if (key == "entityId") {
        if (dup(1u << 2)) { out.all_ok = false; return true; }
        ws();
        has_ei = true;
        if (p < end && *p == '"') {
          if (!string_token(sval, ei_s, ei_e)) { out.all_ok = false; return true; }
          ei_empty = sval.empty();
        } else if (int_token(ei_s, ei_e)) {
          ei_int = true; ei_empty = false;
        } else { out.all_ok = false; return true; }
      } else if (key == "targetEntityType") {
        if (dup(1u << 3)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else if (p < end && *p == '"') {
          if (!string_token(tet_val, tet_s, tet_e)) { out.all_ok = false; return true; }
          tet_null = false;
        } else { out.all_ok = false; return true; }
      } else if (key == "targetEntityId") {
        if (dup(1u << 4)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else if (p < end && *p == '"') {
          if (!string_token(sval, tei_s, tei_e)) { out.all_ok = false; return true; }
          tei_null = false;
          if (sval.empty()) { out.all_ok = false; return true; }
        } else if (int_token(tei_s, tei_e)) { tei_null = false; tei_int = true; }
        else { out.all_ok = false; return true; }
      } else if (key == "properties") {
        if (dup(1u << 5)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else if (!props_object(pr_s, pr_e, pr_keys, pio_key))
          { out.all_ok = false; return true; }
      } else if (key == "tags") {
        if (dup(1u << 6)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else if (!string_array(tg_s, tg_e)) { out.all_ok = false; return true; }
      } else if (key == "prId") {
        if (dup(1u << 7)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else if (!string_token(sval, prid_s, prid_e))
          { out.all_ok = false; return true; }
      } else if (key == "eventTime") {
        if (dup(1u << 8)) { out.all_ok = false; return true; }
        ws();
        if (is_null()) { if (!strict_value()) { out.all_ok = false; return true; } }
        else {
          if (!string_token(sval, d0, d1)) { out.all_ok = false; return true; }
          has_time = true;
          if (!strict_iso_us(sval, t_us)) { out.all_ok = false; return true; }
        }
      } else if (key == "eventId") {
        out.all_ok = false;  // client-supplied id → upsert semantics → python
        return true;
      } else if (key == "creationTime") {
        if (dup(1u << 9)) { out.all_ok = false; return true; }
        // server-assigned: the event server pops it from client payloads
        if (!strict_value()) { out.all_ok = false; return true; }
      } else {
        // unknown keys ignored by from_json, but json.loads still
        // validates them — strict or bust
        if (!strict_value()) { out.all_ok = false; return true; }
      }
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      return false;
    }

    // -- validation (mirror of event.py validate_event + from_json) ------
    if (ev_s < 0 || ev.empty() || et_s < 0 || etype.empty() || !has_ei ||
        ei_empty || pio_key)
      { out.all_ok = false; return true; }
    if (tet_null != tei_null) { out.all_ok = false; return true; }
    if (!tet_null && tet_val.empty()) { out.all_ok = false; return true; }
    if (ev[0] == '$') {
      bool special = (ev == "$set" || ev == "$unset" || ev == "$delete");
      bool props_empty = (pr_s < 0 || pr_keys == 0);
      if (!special || !tet_null ||
          (ev == "$unset" && props_empty) ||
          (ev == "$delete" && !props_empty))
        { out.all_ok = false; return true; }
    }
    if (etype.rfind("pio_", 0) == 0 ||
        (!tet_null && tet_val.rfind("pio_", 0) == 0))
      { out.all_ok = false; return true; }

    // -- canonical line (field order matches Event.to_json) --------------
    std::string& L = out.lines;
    L += "{\"eventId\": \"";
    L.append(id32, 32);
    L += "\", \"event\": ";
    L.append(base + ev_s, ev_e - ev_s);
    L += ", \"entityType\": ";
    L.append(base + et_s, et_e - et_s);
    L += ", \"entityId\": ";
    if (ei_int) { L += '"'; L.append(base + ei_s, ei_e - ei_s); L += '"'; }
    else L.append(base + ei_s, ei_e - ei_s);
    if (!tet_null) {
      L += ", \"targetEntityType\": ";
      L.append(base + tet_s, tet_e - tet_s);
      L += ", \"targetEntityId\": ";
      if (tei_int) { L += '"'; L.append(base + tei_s, tei_e - tei_s); L += '"'; }
      else L.append(base + tei_s, tei_e - tei_s);
    }
    L += ", \"properties\": ";
    if (pr_s >= 0) L.append(base + pr_s, pr_e - pr_s);
    else L += "{}";
    L += ", \"eventTime\": \"";
    if (has_time) format_us(t_us, L);
    else L += creation;  // server time when the client omitted eventTime
    L += "\"";
    if (tg_s >= 0) {
      L += ", \"tags\": ";
      L.append(base + tg_s, tg_e - tg_s);
    }
    if (prid_s >= 0) {
      L += ", \"prId\": ";
      L.append(base + prid_s, prid_e - prid_s);
    }
    L += ", \"creationTime\": \"";
    L += creation;
    L += "\"}\n";
    return true;
  }

};

}  // namespace

extern "C" {

void* pio_ingest_batch(const char* buf, int64_t len, const char* ids_hex,
                       int64_t n_ids, const char* creation_iso,
                       char* errbuf, int64_t errbuf_len) {
  auto* out = new IngestOut();
  IngestParser ps(buf, len);
  std::string creation(creation_iso ? creation_iso : "");
  ps.ws();
  if (ps.p >= ps.end || *ps.p != '[') {
    out->err = "batch body must be a JSON array";
    if (errbuf && errbuf_len > 0)
      snprintf(errbuf, errbuf_len, "%s", out->err.c_str());
    out->all_ok = false;
    return out;
  }
  ++ps.p;
  ps.ws();
  if (ps.p < ps.end && *ps.p == ']') {
    ++ps.p;
  } else {
    while (true) {
      if (out->n_items >= n_ids) { out->all_ok = false; break; }
      if (!ps.item(*out, ids_hex + 32 * out->n_items, creation)) {
        out->err = ps.err.empty() ? "malformed event object" : ps.err;
        if (errbuf && errbuf_len > 0)
          snprintf(errbuf, errbuf_len, "%s", out->err.c_str());
        out->all_ok = false;
        break;
      }
      ++out->n_items;
      if (!out->all_ok) break;  // python will redo the whole request
      ps.ws();
      if (ps.p < ps.end && *ps.p == ',') { ++ps.p; continue; }
      if (ps.p < ps.end && *ps.p == ']') { ++ps.p; break; }
      out->err = "expected ',' or ']'";
      out->all_ok = false;
      break;
    }
  }
  if (out->all_ok) {
    ps.ws();
    if (ps.p != ps.end) out->all_ok = false;  // trailing garbage
  }
  return out;
}

int64_t pio_ingest_count(void* h) {
  return static_cast<IngestOut*>(h)->n_items;
}

int32_t pio_ingest_all_ok(void* h) {
  return static_cast<IngestOut*>(h)->all_ok ? 1 : 0;
}

const char* pio_ingest_lines(void* h, int64_t* out_len) {
  auto* o = static_cast<IngestOut*>(h);
  if (out_len) *out_len = static_cast<int64_t>(o->lines.size());
  return o->lines.data();
}

void pio_ingest_free(void* h) { delete static_cast<IngestOut*>(h); }

}  // extern "C"


// ===========================================================================
// CCO host partition: deduped (user, item) pairs — already sorted by user
// from the packed-key dedupe — laid out as [n_ranges, E] slabs of (local
// offset, item) uint16, with heavy users routed to their own rank-range
// slabs, plus the per-item distinct-user counts, all in two linear passes.
// The numpy version (fancy-index scatter writes + bincounts) measured
// ~1.0 s of the UR train's host time at 10M pairs; this runs ~10x faster.
// ===========================================================================

namespace {

struct CcoPart {
  std::vector<uint16_t> light_eu, light_ei;
  std::vector<uint16_t> heavy_eu, heavy_ei;
  std::vector<int64_t> item_counts;
  int64_t light_e = 1, heavy_e = 1;
  int64_t n_ranges = 0, h_ranges = 0;
};

}  // namespace

extern "C" {

// u/ii: deduped pairs SORTED BY USER; rank: per-user heavy rank or NULL.
// Requires u_chunk < 0xFFFF and n_items <= 0xFFFF (uint16 wire — the
// caller falls back to the numpy path otherwise).
void* pio_cco_partition(const int32_t* u, const int32_t* ii, int64_t n,
                        const int32_t* rank, int64_t n_users,
                        int32_t u_chunk, int64_t n_ranges, int64_t n_items,
                        int32_t h_chunk, int64_t h_ranges) {
  auto* out = new CcoPart();
  out->n_ranges = n_ranges;
  out->h_ranges = h_ranges;
  out->item_counts.assign(static_cast<size_t>(n_items), 0);
  std::vector<int64_t> lcount(static_cast<size_t>(n_ranges), 0);
  std::vector<int64_t> hcount(static_cast<size_t>(h_ranges), 0);
  const int64_t max_u = n_ranges * u_chunk;
  // pass 1: per-range counts (+ per-item counts over ALL kept pairs)
  for (int64_t j = 0; j < n; ++j) {
    int32_t uu = u[j];
    int32_t it = ii[j];
    if (uu < 0 || it < 0 || it >= n_items) continue;
    ++out->item_counts[it];
    int32_t r;
    if (rank && uu < n_users && (r = rank[uu]) >= 0) {
      ++hcount[r / h_chunk];
    } else if (uu < max_u) {
      ++lcount[uu / u_chunk];
    }
  }
  for (int64_t c : lcount) out->light_e = std::max(out->light_e, c);
  for (int64_t c : hcount) out->heavy_e = std::max(out->heavy_e, c);
  // pass 2: fill (sentinel offset = chunk width, item 0)
  out->light_eu.assign(static_cast<size_t>(n_ranges * out->light_e),
                       static_cast<uint16_t>(u_chunk));
  out->light_ei.assign(static_cast<size_t>(n_ranges * out->light_e), 0);
  if (h_ranges) {
    out->heavy_eu.assign(static_cast<size_t>(h_ranges * out->heavy_e),
                         static_cast<uint16_t>(h_chunk));
    out->heavy_ei.assign(static_cast<size_t>(h_ranges * out->heavy_e), 0);
  }
  std::vector<int64_t> lpos(static_cast<size_t>(n_ranges), 0);
  std::vector<int64_t> hpos(static_cast<size_t>(h_ranges), 0);
  for (int64_t j = 0; j < n; ++j) {
    int32_t uu = u[j];
    int32_t it = ii[j];
    if (uu < 0 || it < 0 || it >= n_items) continue;
    int32_t r = -1;
    if (rank && uu < n_users && (r = rank[uu]) >= 0) {
      int64_t rg = r / h_chunk;
      int64_t at = rg * out->heavy_e + hpos[rg]++;
      out->heavy_eu[at] = static_cast<uint16_t>(r - rg * h_chunk);
      out->heavy_ei[at] = static_cast<uint16_t>(it);
    } else if (uu < max_u) {
      int64_t rg = uu / u_chunk;
      int64_t at = rg * out->light_e + lpos[rg]++;
      out->light_eu[at] = static_cast<uint16_t>(uu - rg * u_chunk);
      out->light_ei[at] = static_cast<uint16_t>(it);
    }
  }
  return out;
}

int64_t pio_ccop_dim(void* h, int32_t which) {
  auto* o = static_cast<CcoPart*>(h);
  switch (which) {
    case 0: return o->light_e;
    case 1: return o->heavy_e;
    default: return 0;
  }
}

const uint16_t* pio_ccop_slab(void* h, int32_t which) {
  auto* o = static_cast<CcoPart*>(h);
  switch (which) {
    case 0: return o->light_eu.data();
    case 1: return o->light_ei.data();
    case 2: return o->heavy_eu.data();
    case 3: return o->heavy_ei.data();
    default: return nullptr;
  }
}

const int64_t* pio_ccop_item_counts(void* h) {
  return static_cast<CcoPart*>(h)->item_counts.data();
}

void pio_ccop_free(void* h) { delete static_cast<CcoPart*>(h); }

}  // extern "C"

// ===========================================================================
// CCO pair dedupe: raw (user, item) events → distinct pairs sorted by
// (user, item) + per-user distinct counts, via counting-sort by user and
// small per-user sorts — two linear passes instead of np.unique's global
// comparison sort (0.39 s at the UR bench's 10M events).
// ===========================================================================

namespace {

struct PairDedupe {
  std::vector<int32_t> du, di;      // deduped pairs, (user, item)-sorted
  std::vector<int64_t> per_user;    // distinct-pair count per user
};

}  // namespace

extern "C" {

void* pio_pair_dedupe(const int32_t* u, const int32_t* ii, int64_t n,
                      int64_t n_users, int64_t n_items) {
  auto* out = new PairDedupe();
  out->per_user.assign(static_cast<size_t>(n_users), 0);
  // pass 1: events per user (invalid ids dropped, matching the numpy path)
  std::vector<int64_t> count(static_cast<size_t>(n_users), 0);
  for (int64_t j = 0; j < n; ++j) {
    int32_t uu = u[j], it = ii[j];
    if (uu < 0 || uu >= n_users || it < 0 || it >= n_items) continue;
    ++count[uu];
  }
  std::vector<int64_t> start(static_cast<size_t>(n_users) + 1, 0);
  for (int64_t s = 0; s < n_users; ++s) start[s + 1] = start[s] + count[s];
  // pass 2: bucket items by user
  std::vector<int32_t> items(static_cast<size_t>(start[n_users]));
  std::vector<int64_t> cursor(start.begin(), start.end() - 1);
  for (int64_t j = 0; j < n; ++j) {
    int32_t uu = u[j], it = ii[j];
    if (uu < 0 || uu >= n_users || it < 0 || it >= n_items) continue;
    items[cursor[uu]++] = it;
  }
  // per-user sort + adjacent-unique emit (matches np.unique's
  // (user, item) order exactly — layout-identity tested)
  out->du.reserve(items.size());
  out->di.reserve(items.size());
  for (int64_t s = 0; s < n_users; ++s) {
    int32_t* lo = items.data() + start[s];
    int32_t* hi = items.data() + start[s + 1];
    if (lo == hi) continue;
    std::sort(lo, hi);
    int32_t prev = -1;
    int64_t distinct = 0;
    for (int32_t* q = lo; q < hi; ++q) {
      if (*q != prev) {
        out->du.push_back(static_cast<int32_t>(s));
        out->di.push_back(*q);
        prev = *q;
        ++distinct;
      }
    }
    out->per_user[s] = distinct;
  }
  return out;
}

int64_t pio_pdd_count(void* h) {
  return static_cast<int64_t>(static_cast<PairDedupe*>(h)->du.size());
}

const int32_t* pio_pdd_users(void* h) {
  return static_cast<PairDedupe*>(h)->du.data();
}

const int32_t* pio_pdd_items(void* h) {
  return static_cast<PairDedupe*>(h)->di.data();
}

const int64_t* pio_pdd_per_user(void* h) {
  return static_cast<PairDedupe*>(h)->per_user.data();
}

void pio_pdd_free(void* h) { delete static_cast<PairDedupe*>(h); }

}  // extern "C"
